"""Vision multimodal numerics: SigLIP tower + gemma3 projector + soft-token
splice vs HF Gemma3ForConditionalGeneration (torch cpu), random-init tiny
checkpoints — the same strategy as tests/test_model.py."""

import numpy as np
import pytest

import jax.numpy as jnp


@pytest.fixture(scope="module")
def mm_ckpt(tmp_path_factory):
    import torch
    from transformers import (
        Gemma3Config,
        Gemma3ForConditionalGeneration,
    )

    torch.manual_seed(0)
    cfg = Gemma3Config(
        text_config=dict(
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            head_dim=16,
            query_pre_attn_scalar=16,
            sliding_window=8,
            rope_local_base_freq=10000.0,
            rope_theta=1000000.0,
            max_position_embeddings=256,
        ),
        vision_config=dict(
            hidden_size=32,
            num_hidden_layers=2,
            num_attention_heads=2,
            intermediate_size=64,
            image_size=56,
            patch_size=14,
            num_channels=3,
        ),
        mm_tokens_per_image=4,
        boi_token_index=88,
        eoi_token_index=89,
        image_token_index=90,
    )
    model = Gemma3ForConditionalGeneration(cfg)
    d = tmp_path_factory.mktemp("mm") / "gemma3-mm"
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def test_vision_tower_and_projector_match_hf(mm_ckpt):
    import torch
    from transformers import Gemma3ForConditionalGeneration

    from localai_tfp_tpu.models.hf_loader import load_multimodal
    from localai_tfp_tpu.models.vision import encode_images

    vspec, vparams, mm = load_multimodal(mm_ckpt, dtype=jnp.float32)
    assert mm["mm_tokens"] == 4 and mm["image_token"] == 90

    rng = np.random.default_rng(0)
    pixels = rng.normal(size=(1, 3, 56, 56)).astype(np.float32)

    hf = Gemma3ForConditionalGeneration.from_pretrained(
        mm_ckpt, torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        ref = hf.get_image_features(torch.tensor(pixels)).numpy()

    got = np.asarray(encode_images(vspec, vparams, jnp.asarray(pixels)))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


def test_multimodal_logits_match_hf(mm_ckpt):
    import torch
    from transformers import Gemma3ForConditionalGeneration

    from localai_tfp_tpu.models.hf_loader import load_multimodal, load_params
    from localai_tfp_tpu.models.transformer import KVCache, forward
    from localai_tfp_tpu.models.vision import encode_images

    spec, params = load_params(mm_ckpt, dtype=jnp.float32)
    vspec, vparams, mm = load_multimodal(mm_ckpt, dtype=jnp.float32)

    rng = np.random.default_rng(1)
    pixels = rng.normal(size=(1, 3, 56, 56)).astype(np.float32)
    # prompt: text, <boi>, 4 soft tokens, <eoi>, text
    ids = [5, 17, mm["boi_token"]] + [mm["image_token"]] * 4 \
        + [mm["eoi_token"], 23, 42]
    tokens = np.asarray([ids], np.int32)

    hf = Gemma3ForConditionalGeneration.from_pretrained(
        mm_ckpt, torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(tokens, dtype=torch.long),
                 pixel_values=torch.tensor(pixels)).logits.numpy()

    soft_tokens = np.asarray(
        encode_images(vspec, vparams, jnp.asarray(pixels)))[0]  # [4, D]
    T = tokens.shape[1]
    emb = np.zeros((1, T, spec.d_model), np.float32)
    mask = tokens == mm["image_token"]
    emb[0, mask[0]] = soft_tokens
    cache = KVCache.create(spec, 1, 32, jnp.float32)
    logits, _ = forward(
        spec, params, jnp.asarray(tokens), jnp.zeros((1,), jnp.int32),
        cache, jnp.zeros((1,), jnp.int32),
        soft=(jnp.asarray(emb), jnp.asarray(mask)),
    )
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=3e-4, atol=3e-4)


def test_engine_multimodal_generation_and_no_prefix_leak(mm_ckpt):
    """Soft embeds flow through chunked prefill + fused final prefill, and
    a later TEXT request with the same token ids must NOT reuse the
    image-conditioned KV prefix (soft ids collide across images)."""
    import jax

    from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
    from localai_tfp_tpu.engine.tokenizer import load_tokenizer
    from localai_tfp_tpu.models.hf_loader import load_multimodal, load_params
    from localai_tfp_tpu.models.vision import encode_images

    spec, params = load_params(mm_ckpt, dtype=jnp.float32)
    vspec, vparams, mm = load_multimodal(mm_ckpt, dtype=jnp.float32)
    tok = load_tokenizer(mm_ckpt)

    rng = np.random.default_rng(2)
    eng = LLMEngine(spec, params, tok, n_slots=2, max_seq=64,
                    prefill_buckets=(8, 16), cache_dtype=jnp.float32,
                    autostart=False)
    eng.start()
    try:
        def mm_request(seed):
            pixels = rng.normal(size=(1, 3, 56, 56)).astype(np.float32)
            soft = np.asarray(
                encode_images(vspec, vparams, jnp.asarray(pixels)))[0]
            ids = [2, 5, 17, mm["boi_token"]] \
                + [mm["image_token"]] * mm["mm_tokens"] \
                + [mm["eoi_token"], 23, 42]
            pos = np.arange(4, 4 + mm["mm_tokens"], dtype=np.int32)
            return GenRequest(
                prompt_ids=ids, max_tokens=6, ignore_eos=True,
                soft_embeds=soft.astype(np.float32), soft_positions=pos,
            ), ids

        r1, ids = mm_request(0)
        ev1 = eng.generate(r1)
        assert ev1.finish_reason == "length", ev1.error
        toks1 = eng.slots  # generation happened
        # same token ids, DIFFERENT image -> must re-prefill, and with a
        # different image the first sampled token may differ; at minimum
        # the slot must not report a reusable prefix
        assert all(not s.cache_tokens for s in eng.slots if not s.active)

        r2, _ = mm_request(1)
        ev2 = eng.generate(r2)
        assert ev2.finish_reason == "length", ev2.error
        assert ev2.prompt_tokens == len(ids)

        # text-only request still healthy afterwards
        ev3 = eng.generate(GenRequest(prompt_ids=[2, 5, 17, 23],
                                      max_tokens=4, ignore_eos=True))
        assert ev3.finish_reason == "length", ev3.error
    finally:
        eng.close()


def test_templating_collects_media_markers():
    from localai_tfp_tpu.config.model_config import ModelConfig
    from localai_tfp_tpu.engine.templating import Evaluator

    cfg = ModelConfig(name="m")
    cfg.template.chat_message = "{{.RoleName}}: {{.Content}}"
    cfg.template.chat = "{{.Input}}"
    ev = Evaluator()
    media: list = []
    out = ev.template_messages(cfg, [
        {"role": "user", "content": [
            {"type": "text", "text": "look at "},
            {"type": "image_url",
             "image_url": {"url": "data:image/png;base64,aGk="}},
            {"type": "text", "text": " please"},
        ]},
    ], media=media)
    assert "[img-0]" in out and "look at " in out
    assert len(media) == 1


# ------------------------- CLIP / LLaVA family -------------------------


@pytest.fixture(scope="module")
def llava_ckpt(tmp_path_factory):
    import torch
    from transformers import LlavaConfig, LlavaForConditionalGeneration

    torch.manual_seed(0)
    cfg = LlavaConfig(
        text_config=dict(
            model_type="llama",
            vocab_size=128,
            hidden_size=64,
            intermediate_size=128,
            num_hidden_layers=2,
            num_attention_heads=4,
            num_key_value_heads=2,
            max_position_embeddings=256,
        ),
        vision_config=dict(
            model_type="clip_vision_model",
            hidden_size=32,
            num_hidden_layers=3,
            num_attention_heads=2,
            intermediate_size=64,
            image_size=28,
            patch_size=14,
            num_channels=3,
        ),
        image_token_index=90,
        vision_feature_layer=-2,
        vision_feature_select_strategy="default",
    )
    model = LlavaForConditionalGeneration(cfg)
    d = tmp_path_factory.mktemp("mm") / "llava-mm"
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def test_llava_tower_and_projector_match_hf(llava_ckpt):
    """CLIP tower (penultimate layer, CLS dropped) + MLP projector vs HF
    LlavaForConditionalGeneration.get_image_features (VERDICT r3 next
    #6: llava-class mmproj vision)."""
    import torch
    from transformers import LlavaForConditionalGeneration

    from localai_tfp_tpu.models.hf_loader import load_multimodal
    from localai_tfp_tpu.models.vision import encode_images

    vspec, vparams, mm = load_multimodal(llava_ckpt, dtype=jnp.float32)
    assert vspec.family == "clip"
    assert mm["image_token"] == 90 and mm["boi_token"] is None
    assert mm["mm_tokens"] == 4  # (28/14)^2 patches

    rng = np.random.default_rng(0)
    pixels = rng.normal(size=(2, 3, 28, 28)).astype(np.float32)

    hf = LlavaForConditionalGeneration.from_pretrained(
        llava_ckpt, torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        ref = hf.get_image_features(torch.tensor(pixels))
        if isinstance(ref, (list, tuple)):  # per-image list in newer HF
            ref = torch.stack(list(ref))
        ref = ref.numpy()

    got = np.asarray(encode_images(vspec, vparams, jnp.asarray(pixels)))
    np.testing.assert_allclose(got, ref.reshape(got.shape),
                               rtol=2e-4, atol=2e-4)


def test_llava_logits_match_hf(llava_ckpt):
    """Soft-token splice over the <image> placeholder reproduces HF
    multimodal logits."""
    import torch
    from transformers import LlavaForConditionalGeneration

    from localai_tfp_tpu.models.hf_loader import load_multimodal, load_params
    from localai_tfp_tpu.models.transformer import KVCache, forward
    from localai_tfp_tpu.models.vision import encode_images

    spec, params = load_params(llava_ckpt, dtype=jnp.float32)
    vspec, vparams, mm = load_multimodal(llava_ckpt, dtype=jnp.float32)

    rng = np.random.default_rng(1)
    pixels = rng.normal(size=(1, 3, 28, 28)).astype(np.float32)
    ids = [5, 17] + [mm["image_token"]] * mm["mm_tokens"] + [23, 42]
    tokens = np.asarray([ids], np.int32)

    hf = LlavaForConditionalGeneration.from_pretrained(
        llava_ckpt, torch_dtype=torch.float32)
    hf.eval()
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(tokens, dtype=torch.long),
                 pixel_values=torch.tensor(pixels)).logits.numpy()

    soft_tokens = np.asarray(
        encode_images(vspec, vparams, jnp.asarray(pixels)))[0]
    T = tokens.shape[1]
    emb = np.zeros((1, T, spec.d_model), np.float32)
    mask = tokens == mm["image_token"]
    emb[0, mask[0]] = soft_tokens
    cache = KVCache.create(spec, 1, 32, jnp.float32)
    logits, _ = forward(
        spec, params, jnp.asarray(tokens), jnp.zeros((1,), jnp.int32),
        cache, jnp.zeros((1,), jnp.int32),
        soft=(jnp.asarray(emb), jnp.asarray(mask)),
    )
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=3e-4,
                               atol=3e-4)


def test_llava_worker_splices_images_without_boi(llava_ckpt):
    """The LLM worker's [img-N] splice must handle the boi/eoi-less
    llava protocol end to end (image chat through the backend)."""
    from localai_tfp_tpu.workers.base import ModelLoadOptions, PredictOptions
    from localai_tfp_tpu.workers.llm import JaxLLMBackend

    b = JaxLLMBackend()
    res = b.load_model(ModelLoadOptions(
        model=llava_ckpt, context_size=64, batch_slots=2,
        dtype="float32"))
    assert res.success, res.message
    assert b.vision is not None
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.new("RGB", (32, 32), (200, 30, 30)).save(buf, format="PNG")
    png = buf.getvalue()
    reply = b.predict(PredictOptions(
        prompt="look: [img-0] describe", tokens=4, ignore_eos=True,
        images=[png]))
    assert not reply.error
    assert reply.tokens == 4
    b.shutdown()
