"""Config hot-reload, external/remote backends, explorer, store client
(ref: config_file_watcher.go, external backends, core/explorer,
core/clients/store.go)."""

import asyncio
import json
import os
import threading
import time

import numpy as np
import pytest
from aiohttp import web
from aiohttp.test_utils import TestServer

from localai_tfp_tpu.config.watcher import ConfigWatcher
from localai_tfp_tpu.parallel.explorer import (
    DiscoveryServer, ExplorerDB, NetworkEntry,
)
from localai_tfp_tpu.workers.base import ModelLoadOptions, PredictOptions
from localai_tfp_tpu.workers.remote import RemoteOpenAIBackend


def test_watcher_detects_changes(tmp_path):
    seen = []
    w = ConfigWatcher(str(tmp_path), interval=0.05)
    w.watch("api_keys.json", lambda d: seen.append(d))
    (tmp_path / "api_keys.json").write_text('["k1"]')
    w.start()
    try:
        time.sleep(0.1)
        assert seen and seen[-1] == ["k1"]
        # rewrite -> change fires (ensure mtime moves)
        time.sleep(0.05)
        p = tmp_path / "api_keys.json"
        p.write_text('["k1", "k2"]')
        os.utime(p, (time.time() + 2, time.time() + 2))
        deadline = time.time() + 3
        while time.time() < deadline and (not seen or
                                          seen[-1] != ["k1", "k2"]):
            time.sleep(0.05)
        assert seen[-1] == ["k1", "k2"]
        # deletion -> handler gets None
        p.unlink()
        deadline = time.time() + 3
        while time.time() < deadline and seen[-1] is not None:
            time.sleep(0.05)
        assert seen[-1] is None
    finally:
        w.stop()


def test_watcher_ignores_bad_json(tmp_path):
    seen = []
    w = ConfigWatcher(str(tmp_path), interval=0.05)
    w.watch("api_keys.json", lambda d: seen.append(d))
    (tmp_path / "api_keys.json").write_text("{not json")
    w.start()
    time.sleep(0.2)
    w.stop()
    assert seen == []


# ------------------------------------------------------------ remote backend


@pytest.fixture()
def upstream():
    """A minimal OpenAI-compatible upstream served in a thread."""
    loop = asyncio.new_event_loop()

    async def completions(request):
        body = await request.json()
        if body.get("stream"):
            resp = web.StreamResponse()
            resp.headers["Content-Type"] = "text/event-stream"
            await resp.prepare(request)
            for tok in ("he", "llo"):
                await resp.write(
                    b"data: " + json.dumps(
                        {"choices": [{"text": tok}]}).encode() + b"\n\n")
            await resp.write(
                b"data: " + json.dumps(
                    {"choices": [{"text": "",
                                  "finish_reason": "stop"}]}).encode()
                + b"\n\ndata: [DONE]\n\n")
            await resp.write_eof()
            return resp
        return web.json_response({
            "choices": [{"text": f"echo:{body.get('prompt')}",
                         "finish_reason": "stop"}],
            "usage": {"completion_tokens": 2, "prompt_tokens": 3},
        })

    async def embeddings(request):
        return web.json_response(
            {"data": [{"embedding": [0.1, 0.2, 0.3]}]})

    app = web.Application()
    app.router.add_post("/v1/completions", completions)
    app.router.add_post("/v1/embeddings", embeddings)
    server = TestServer(app)
    loop.run_until_complete(server.start_server())
    url = f"http://127.0.0.1:{server.port}"

    done = threading.Event()

    def pump():  # keep the loop alive for sync urllib callers
        async def wait():
            while not done.is_set():
                await asyncio.sleep(0.02)
        loop.run_until_complete(wait())

    t = threading.Thread(target=pump, daemon=True)
    t.start()
    yield url
    done.set()
    t.join(timeout=5)
    loop.run_until_complete(server.close())
    loop.close()


def test_remote_backend_predict(upstream):
    b = RemoteOpenAIBackend()
    res = b.load_model(ModelLoadOptions(
        model="m", extra={"base_url": upstream}))
    assert res.success, res.message
    out = b.predict(PredictOptions(prompt="hi", tokens=4))
    assert out.message == "echo:hi"
    assert out.prompt_tokens == 3

    chunks = list(b.predict_stream(PredictOptions(prompt="x")))
    text = "".join(c.message for c in chunks)
    assert text == "hello"
    assert chunks[-1].finish_reason == "stop"

    emb = b.embedding(PredictOptions(embeddings="v"))
    assert emb.embeddings == [0.1, 0.2, 0.3]


def test_remote_backend_requires_url():
    b = RemoteOpenAIBackend()
    assert not b.load_model(ModelLoadOptions(model="m")).success


# ---------------------------------------------------------------- explorer


def test_explorer_db_roundtrip(tmp_path):
    db = ExplorerDB(str(tmp_path / "explorer.json"))
    db.add(NetworkEntry(name="net1", url="http://x", description="d"))
    db2 = ExplorerDB(str(tmp_path / "explorer.json"))
    assert [e.name for e in db2.all()] == ["net1"]
    assert db2.remove("net1")
    assert not db2.remove("net1")


def test_explorer_discovery_failure_eviction(tmp_path):
    db = ExplorerDB(str(tmp_path / "e.json"))
    db.add(NetworkEntry(name="dead", url="http://127.0.0.1:1"))
    disc = DiscoveryServer(db)
    for _ in range(3):
        disc.sweep()
    assert db.all() == []  # evicted after FAILURE_THRESHOLD failures
