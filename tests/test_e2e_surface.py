"""One-pass E2E sweep over the whole REST surface against a single live
in-process server with every worker family mounted (the reference's
e2e-aio suite shape — tests/e2e-aio/e2e_test.go:19-263 exercises every
endpoint against the shipped container)."""

import asyncio
import hashlib
import io
import json
import wave

import numpy as np
import pytest
import yaml
from aiohttp import FormData
from aiohttp.test_utils import TestClient, TestServer

from localai_tfp_tpu.config.app_config import ApplicationConfig
from localai_tfp_tpu.server.app import build_app
from localai_tfp_tpu.server.state import Application


@pytest.fixture(scope="module")
def srv(tmp_path_factory):
    root = tmp_path_factory.mktemp("aio")
    models = root / "models"
    models.mkdir()

    import torch
    from transformers import (
        BertConfig, BertModel, LlamaConfig, LlamaForCausalLM,
        WhisperConfig, WhisperForConditionalGeneration,
    )

    torch.manual_seed(0)
    LlamaForCausalLM(LlamaConfig(
        vocab_size=300, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256,
    )).save_pretrained(models / "llm-ckpt", safe_serialization=True)
    BertModel(BertConfig(
        vocab_size=300, hidden_size=32, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=64,
        max_position_embeddings=128,
    )).save_pretrained(models / "bert-ckpt", safe_serialization=True)
    WhisperForConditionalGeneration(WhisperConfig(
        vocab_size=1000, d_model=64, encoder_layers=2, decoder_layers=2,
        encoder_attention_heads=4, decoder_attention_heads=4,
        encoder_ffn_dim=128, decoder_ffn_dim=128,
        max_source_positions=1500, max_target_positions=448,
        num_mel_bins=80, decoder_start_token_id=997, eos_token_id=998,
        pad_token_id=998, bos_token_id=998,
    )).save_pretrained(models / "whisper-ckpt", safe_serialization=True)

    for name, cfg in {
        "llm": {"backend": "jax-llm",
                "parameters": {"model": "llm-ckpt", "max_tokens": 6},
                "context_size": 128, "max_batch_slots": 2,
                "dtype": "float32",
                "template": {"completion": "{{.Input}}",
                             "chat": "{{.Input}}"}},
        "emb": {"backend": "jax-embeddings",
                "parameters": {"model": "bert-ckpt"}},
        "rr": {"backend": "jax-rerank", "parameters": {"model": "bert-ckpt"}},
        "stt": {"backend": "jax-whisper",
                "parameters": {"model": "whisper-ckpt"}},
        "voice": {"backend": "jax-tts"},
        "vadm": {"backend": "jax-vad"},
        "img": {"backend": "jax-diffusion", "options": ["steps=2"]},
    }.items():
        (models / f"{name}.yaml").write_text(
            yaml.safe_dump({"name": name, **cfg}))

    loop = asyncio.new_event_loop()
    cfg = ApplicationConfig(
        models_path=str(models),
        generated_content_dir=str(root / "generated"),
        upload_dir=str(root / "uploads"),
        config_dir=str(root / "configuration"),
    )
    app = build_app(Application(cfg))
    tc = TestClient(TestServer(app), loop=loop)
    loop.run_until_complete(tc.start_server())

    def req(method, path, **kw):
        async def go():
            r = await tc.request(method, path, **kw)
            body = await r.read()
            return r.status, body
        return loop.run_until_complete(go())

    yield req
    loop.run_until_complete(tc.close())
    loop.close()


def _json(body):
    return json.loads(body)


def test_models_and_system(srv):
    status, body = srv("GET", "/v1/models")
    assert status == 200
    names = {m["id"] for m in _json(body)["data"]}
    assert {"llm", "emb", "rr", "stt", "voice", "vadm", "img"} <= names
    assert srv("GET", "/system")[0] == 200
    assert srv("GET", "/metrics")[0] == 200
    assert srv("GET", "/version")[0] == 200


def test_chat_completion_embeddings(srv):
    status, body = srv("POST", "/v1/chat/completions", json={
        "model": "llm", "max_tokens": 4,
        "messages": [{"role": "user", "content": "hi"}]})
    assert status == 200 and _json(body)["choices"]
    status, body = srv("POST", "/v1/completions", json={
        "model": "llm", "prompt": "abc", "max_tokens": 4})
    assert status == 200
    status, body = srv("POST", "/v1/embeddings", json={
        "model": "emb", "input": "hello"})
    assert status == 200
    assert len(_json(body)["data"][0]["embedding"]) == 32
    status, body = srv("POST", "/v1/tokenize", json={
        "model": "llm", "content": "hello"})
    assert status == 200


def test_rerank(srv):
    status, body = srv("POST", "/v1/rerank", json={
        "model": "rr", "query": "q", "documents": ["a", "b"],
        "top_n": 2})
    assert status == 200 and len(_json(body)["results"]) == 2


def test_audio_roundtrip(srv):
    sr = 16000
    t = np.arange(sr // 2) / sr
    pcm = (0.4 * np.sin(2 * np.pi * 440 * t) * 32767).astype("<i2")
    buf = io.BytesIO()
    with wave.open(buf, "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes(pcm.tobytes())
    form = FormData()
    form.add_field("model", "stt")
    form.add_field("file", buf.getvalue(), filename="a.wav")
    status, body = srv("POST", "/v1/audio/transcriptions", data=form)
    assert status == 200 and "text" in _json(body)

    status, body = srv("POST", "/v1/audio/speech", json={
        "model": "voice", "input": "hello"})
    assert status == 200 and body[:4] == b"RIFF"
    status, body = srv("POST", "/v1/text-to-speech/alloy", json={
        "model_id": "voice", "text": "hey"})
    assert status == 200 and body[:4] == b"RIFF"

    audio = np.zeros(sr, np.float32)
    audio[sr // 4: sr // 2] = 0.5 * np.sin(
        2 * np.pi * 120 * t[: sr // 4])
    status, body = srv("POST", "/vad", json={
        "model": "vadm", "audio": audio.tolist()})
    assert status == 200 and "segments" in _json(body)


def test_images(srv):
    status, body = srv("POST", "/v1/images/generations", json={
        "model": "img", "prompt": "a tree", "size": "32x32",
        "response_format": "b64_json"})
    assert status == 200
    import base64

    png = base64.b64decode(_json(body)["data"][0]["b64_json"])
    assert png[:8] == b"\x89PNG\r\n\x1a\n"


def test_stores_roundtrip(srv):
    assert srv("POST", "/stores/set", json={
        "keys": [[1.0, 0.0], [0.0, 1.0]], "values": ["a", "b"]})[0] == 200
    status, body = srv("POST", "/stores/find", json={
        "key": [1.0, 0.1], "topk": 1})
    assert status == 200 and _json(body)["values"] == ["a"]


def test_backend_monitor_and_shutdown(srv):
    status, body = srv("GET", "/backend/monitor?model=llm")
    assert status == 200
    out = _json(body)
    assert out["backend"] == "jax-llm" and "cpu_percent" in out
    assert srv("POST", "/backend/shutdown", json={"model": "vadm"})[0] == 200
