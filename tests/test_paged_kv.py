"""Paged KV pool: block-granular HBM allocation + zero-copy sharing.

The dense cache pre-reserves max_seq per slot; the paged pool
(engine/kv_pool.py) backs slots with fixed-size pages from one shared
arena, shares prefix pages by refcount instead of row copy, and must
be byte-identical to the dense path. Covered here:

- allocator churn fuzz: admit/release/share/COW loops never leak a
  page, never double-own a writable page, and refcounts return to zero
- whole-page shared-prefix admission dispatches ZERO kvcopies (the
  zero-copy claim, cross-checked against allocator outcome counters)
- engine-level churn (waves + mid-stream cancels + slot reuse) leaves
  the pool leak-free
- gather/scatter page views are exact inverses and trash-redirected
  writes never land
- paged dispatch payloads stay multihost-replayable (scalars + index
  arrays only — the codec round-trips every record bit-exactly)
- LOCALAI_PAGED_KV on/off produce byte-identical streams
"""

import queue as _q

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
from localai_tfp_tpu.engine.kv_pool import (
    TRASH_PAGE,
    PagePool,
    PagePoolExhausted,
)
from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
from localai_tfp_tpu.models.llm_spec import tiny_spec
from localai_tfp_tpu.models.transformer import init_params


@pytest.fixture(scope="module")
def model():
    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    return spec, params, tk


def _engine(model, **kw):
    spec, params, tk = model
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_seq", 256)
    kw.setdefault("prefill_buckets", (8, 32, 128))
    kw.setdefault("cache_dtype", jnp.float32)
    kw.setdefault("autostart", True)
    return LLMEngine(spec, params, tk, **kw)


class CopySpy:
    """Record every dispatch at the engine._run layer: kind counts for
    the zero-copy regression plus raw payloads for the replay-invariant
    check."""

    def __init__(self, eng):
        self.eng = eng
        self.records: list[tuple[str, dict]] = []
        self._orig = eng._run
        eng._run = self._run

    def _run(self, kind, payload):
        self.records.append((kind, dict(payload)))
        return self._orig(kind, payload)

    def count(self, kind):
        return sum(1 for k, _ in self.records if k == kind)


def _drain(q, timeout=120):
    toks = []
    while True:
        ev = q.get(timeout=timeout)
        if ev.done:
            return toks, ev
        if ev.token_id is not None:
            toks.append(ev.token_id)


def _first_token(q, timeout=120):
    while True:
        ev = q.get(timeout=timeout)
        assert not ev.done, f"finished early: {ev.finish_reason} {ev.error}"
        if ev.token_id is not None:
            return ev


# ---------------------------------------------------------- pool unit


def test_pool_basic_share_cow_lifecycle():
    pool = PagePool(8, 16)
    assert pool.ensure(0, 40) == 3  # 3 pages for 40 tokens
    t0 = list(pool.table(0))
    assert all(pool.writable(p) for p in t0)
    # zero-copy share of the first 2 full pages into slot 1
    assert pool.share(1, 0, 2) == 2
    assert pool.table(1) == t0[:2]
    assert not pool.writable(t0[0]) and not pool.writable(t0[1])
    assert pool.stats().shared == 2
    # aligned frontier (32 = 2 pages): no COW needed, nothing to copy
    assert pool.prepare_write(1, 32) is None
    # unaligned frontier inside a shared page: COW swaps in a fresh page
    pool.share(2, 0, 2)
    cow = pool.prepare_write(2, 24)
    assert cow is not None
    src, dst = cow
    assert src == t0[1] and pool.writable(dst)
    assert pool.table(2)[0] == t0[0]  # untouched shared page remains
    for s in (0, 1, 2):
        pool.drop(s)
    st = pool.stats()
    assert st.in_use == 0 and st.free == st.total and st.refs == 0
    pool.leak_check()


def test_pool_exhaustion_raises_and_stays_consistent():
    pool = PagePool(4, 16)  # 3 data pages
    pool.ensure(0, 48)
    with pytest.raises(PagePoolExhausted):
        pool.ensure(1, 16)
    pool.leak_check()
    pool.drop(0)
    assert pool.ensure(1, 16) == 1
    pool.leak_check()


def test_pool_churn_fuzz():
    """Randomized admit/cancel/evict/preempt churn: after every single
    operation the structural invariants hold (no leaked page, no free
    page referenced, refcount == table references, trash never owned),
    and a full drop returns every refcount to zero."""
    rng = np.random.default_rng(0)
    pool = PagePool(48, 16)
    slots = 8
    cap = 47 * 16
    for _ in range(3000):
        op = int(rng.integers(0, 6))
        s = int(rng.integers(0, slots))
        try:
            if op == 0:  # admit / grow
                pool.ensure(s, int(rng.integers(0, cap // 4)))
            elif op == 1:  # cancel / evict
                pool.drop(s)
            elif op == 2:  # preempt to a shorter prefix
                pool.truncate(s, int(rng.integers(0, cap // 4)))
            elif op == 3:  # zero-copy prefix share
                src = int(rng.integers(0, slots))
                if src != s and pool.held(src):
                    pool.share(
                        s, src,
                        int(rng.integers(0, pool.held(src) + 1)))
            elif op == 4:  # write-frontier privatization (maybe COW)
                held = pool.held(s)
                pos = int(rng.integers(0, held * 16 + 1)) if held else 0
                pool.prepare_write(s, pos)
            else:  # fresh single-page append (decode growth)
                pool.append_fresh(s)
        except PagePoolExhausted:
            pool.drop(s)  # the engine's reclaim analogue
        pool.leak_check()
        # no page may ever be writable through two tables
        owners: dict[int, int] = {}
        for t in pool._tables.values():
            for pg in t:
                owners[pg] = owners.get(pg, 0) + 1
        for pg, n in owners.items():
            assert pg != TRASH_PAGE
            if pool.writable(pg):
                assert n == 1, f"writable page {pg} owned by {n} tables"
    for s in range(slots):
        pool.drop(s)
    st = pool.stats()
    assert st.in_use == 0 and st.refs == 0 and st.free == st.total
    pool.leak_check()


def test_prefix_index_page_run_splits_full_and_tail():
    from localai_tfp_tpu.engine.prefix_index import PrefixIndex

    idx = PrefixIndex()
    idx.set_tokens(0, list(range(40)))
    # 40 matched tokens at 16-token pages: 2 zero-copy full pages + an
    # 8-row tail the engine row-copies
    assert idx.page_run(list(range(40)) + [99], 16) == (2, 8, {0})
    assert idx.page_run([7, 7, 7], 16) == (0, 0, set())


# ------------------------------------------------- transformer views


def test_gather_scatter_kv_pages_roundtrip():
    """gather_kv_pages must reproduce the dense window exactly through
    a shuffled table; scatter_kv_pages must write ONLY the pages its wb
    names, with trash-redirected entries dropped."""
    from localai_tfp_tpu.models.transformer import (
        KVCache, gather_kv_pages, scatter_kv_pages,
    )

    rng = np.random.default_rng(1)
    L, NP, P, F, B, WP = 2, 7, 4, 8, 3, 2
    arena = KVCache(
        k=jnp.asarray(rng.standard_normal((L, NP, P, F)), jnp.float32),
        v=jnp.asarray(rng.standard_normal((L, NP, P, F)), jnp.float32))
    phys = jnp.asarray(rng.permutation(np.arange(1, 7))
                       .reshape(B, WP).astype(np.int32))
    win = gather_kv_pages(arena, phys, P)
    assert win.k.shape == (L, B, WP * P, F)
    pn = np.asarray(phys)
    for b in range(B):
        for p in range(WP):
            np.testing.assert_array_equal(
                np.asarray(win.k)[:, b, p * P:(p + 1) * P],
                np.asarray(arena.k)[:, pn[b, p]])
    # writeback: row 0 persists only its second page; rows 1-2 nothing
    marked = KVCache(k=win.k + 100.0, v=win.v - 100.0)
    wb = np.full((B, WP), TRASH_PAGE, np.int32)
    wb[0, 1] = pn[0, 1]
    out = scatter_kv_pages(arena, marked, jnp.asarray(wb), P)
    np.testing.assert_array_equal(
        np.asarray(out.k)[:, pn[0, 1]],
        np.asarray(arena.k)[:, pn[0, 1]] + 100.0)
    for pg in range(1, NP):  # every other data page untouched
        if pg == pn[0, 1]:
            continue
        np.testing.assert_array_equal(np.asarray(out.k)[:, pg],
                                      np.asarray(arena.k)[:, pg])


# --------------------------------------------------------- engine level


def test_whole_page_shared_prefix_zero_copies(model, monkeypatch):
    """Regression for the zero-copy claim: a sharer whose matched
    prefix is whole-page-aligned admits with NO kvcopy dispatch — the
    pages transfer by refcount — and the allocator's `shared` outcome
    counter (telemetry ground truth) shows exactly those pages."""
    monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
    prefix = list(range(1, 33))  # 32 tokens == 2 full 16-token pages
    tail_a = [40, 41, 42, 43]
    tail_b = [50, 51, 52, 53]  # diverges at its first token
    eng = _engine(model)
    assert eng._paged and eng._page == 16
    spy = CopySpy(eng)
    try:
        qa = eng.submit(GenRequest(prompt_ids=prefix + tail_a,
                                   max_tokens=24, ignore_eos=True))
        _first_token(qa)  # donor prefix committed, slot still DECODE
        shared0 = eng._pool.allocs["shared"]
        qb = eng.submit(GenRequest(prompt_ids=prefix + tail_b,
                                   max_tokens=8, ignore_eos=True))
        _, ev_b = _drain(qb)
        _, ev_a = _drain(qa)
    finally:
        eng.close()
    assert ev_a.finish_reason == "length", ev_a.error
    assert ev_b.finish_reason == "length", ev_b.error
    assert spy.count("kvcopy") == 0, (
        "whole-page prefix share must not row-copy")
    assert eng._pool.allocs["shared"] - shared0 == 2
    assert eng.metrics.prefix_reused_tokens >= len(prefix)


def test_unaligned_prefix_copies_only_the_tail_page(model, monkeypatch):
    """A prefix ending mid-page shares its full pages by reference and
    row-copies exactly ONE page (the sub-page tail)."""
    monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
    prefix = list(range(1, 41))  # 40 tokens: 2 full pages + 8-row tail
    eng = _engine(model)
    assert eng._paged
    spy = CopySpy(eng)
    try:
        qa = eng.submit(GenRequest(prompt_ids=prefix + [60, 61],
                                   max_tokens=24, ignore_eos=True))
        _first_token(qa)
        qb = eng.submit(GenRequest(prompt_ids=prefix + [70, 71],
                                   max_tokens=8, ignore_eos=True))
        _, ev_b = _drain(qb)
        _drain(qa)
    finally:
        eng.close()
    assert ev_b.finish_reason == "length", ev_b.error
    copies = [p for k, p in spy.records if k == "kvcopy"]
    assert len(copies) == 1, copies
    assert copies[0]["n"] == 16  # one whole-page tail copy


def test_engine_churn_no_page_leaks(model, monkeypatch):
    """Waves beyond slot capacity + mid-stream cancels + slot reuse:
    the pool's invariants hold afterwards and dropping the idle
    residents returns every page to the free list."""
    monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
    spec, params, tk = model
    eng = _engine(model)
    assert eng._paged
    rng = np.random.default_rng(2)
    try:
        for wave in range(3):
            n = eng.n_slots + 2  # force queueing + slot reuse/eviction
            reqs = [GenRequest(
                prompt_ids=[int(x) for x in rng.integers(
                    1, 200, int(rng.integers(4, 60)))],
                max_tokens=int(rng.integers(2, 12)),
                ignore_eos=True) for _ in range(n)]
            qs = eng.submit_many(reqs)
            eng.cancel(reqs[0].id)  # cancel one immediately
            for q in qs[1:]:
                _drain(q)
            _drain(qs[0])  # the cancelled one must also terminate
        # settle, then check structural invariants on the idle engine
        import time as _t

        _t.sleep(0.2)
        eng._pool.leak_check()
        for s in eng.slots:
            assert not s.active
            eng._pool.drop(s.idx)
        st = eng._pool.stats()
        assert st.in_use == 0 and st.refs == 0 and st.free == st.total
    finally:
        eng.close()


def test_paged_dispatch_payloads_stay_replayable(model, monkeypatch):
    """Multihost-replay invariant: every dispatch a paged engine emits
    — including the page-table payloads — must survive the broadcast
    codec bit-exactly (scalars + ndarrays only; allocator state never
    crosses)."""
    monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
    from localai_tfp_tpu.parallel import multihost

    prefix = list(range(1, 33))
    eng = _engine(model)
    assert eng._paged
    spy = CopySpy(eng)
    try:
        qa = eng.submit(GenRequest(prompt_ids=prefix + [40],
                                   max_tokens=16, ignore_eos=True))
        _first_token(qa)
        qb = eng.submit(GenRequest(prompt_ids=prefix + [50, 51, 52, 53,
                                                        54, 55, 56, 57],
                                   max_tokens=8, ignore_eos=True))
        _drain(qb)
        _drain(qa)
    finally:
        eng.close()
    assert {"prefill_final"} <= {k for k, _ in spy.records}
    paged_kinds = set()
    for kind, payload in spy.records:
        if "pt" in payload:
            paged_kinds.add(kind)
            assert payload["pt"].dtype == np.int32
            assert payload["wb"].dtype == np.int32
        hdr, buf = multihost.encode_record(kind, payload)
        kind2, out = multihost.decode_record(int(hdr[0]), buf)
        assert kind2 == kind
        assert set(out) == set(payload)

        def same(a, b):
            if isinstance(a, dict):
                return (isinstance(b, dict) and set(a) == set(b)
                        and all(same(v, b[k]) for k, v in a.items()))
            if a is None or isinstance(a, (bool, str)):
                return a == b
            return np.array_equal(np.asarray(a), np.asarray(b))

        for key, val in payload.items():
            assert same(val, out[key]), key
    assert paged_kinds, "no paged dispatch carried a page table"


def test_paged_on_off_byte_identity(model, monkeypatch):
    """LOCALAI_PAGED_KV=off restores the dense cache with byte-identical
    streams — greedy and seeded sampling, shared-prefix traffic."""
    spec, params, tk = model
    prompts = [
        list(range(1, 33)) + [40 + i] for i in range(3)
    ] + [[9, 8, 7, 6, 5]]
    texts = {}
    for mode in ("on", "off"):
        monkeypatch.setenv("LOCALAI_PAGED_KV", mode)
        monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
        eng = _engine(model)
        assert eng._paged == (mode == "on")
        try:
            qs = eng.submit_many(
                [GenRequest(prompt_ids=ids, max_tokens=12,
                            temperature=0.8, top_k=40, seed=7,
                            ignore_eos=True) for ids in prompts]
                + [GenRequest(prompt_ids=[3, 1, 4, 1, 5], max_tokens=12,
                              ignore_eos=True)])
            outs = []
            for q in qs:
                toks, ev = _drain(q)
                assert ev.finish_reason == "length", ev.error
                outs.append(toks)
            texts[mode] = outs
        finally:
            eng.close()
    assert texts["on"] == texts["off"]


def test_pool_pressure_reclaims_idle_residents(model, monkeypatch):
    """An arena sized below worst case serves more slots than the dense
    layout by reclaiming FREE slots' resident prefixes under pressure —
    admission never fails while reclaimable pages exist."""
    monkeypatch.setenv("LOCALAI_KV_PAGE", "16")
    # 13 data pages = 208 tokens of arena for 4 slots x 256 max_seq
    # (dense equivalent: 0.8 slots!)
    eng = _engine(model, kv_pages=14)
    assert eng._paged
    rng = np.random.default_rng(3)
    try:
        for wave in range(4):
            reqs = [GenRequest(
                prompt_ids=[int(x) for x in rng.integers(1, 200, 40)],
                max_tokens=6, ignore_eos=True) for _ in range(4)]
            for q in eng.submit_many(reqs):
                _, ev = _drain(q)
                assert ev.finish_reason == "length", ev.error
        eng._pool.leak_check()
        assert eng._pool.allocs["fresh"] > 0
    finally:
        eng.close()
