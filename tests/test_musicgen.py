"""MusicGen numerics vs HF (torch cpu), tiny random checkpoint: T5
encoder, delay-pattern decoder logits, EnCodec decode, and full greedy
generation parity (ref: transformers backend SoundGeneration :452)."""

import numpy as np
import pytest

import jax.numpy as jnp


@pytest.fixture(scope="module")
def mg_ckpt(tmp_path_factory):
    import torch
    from transformers import (
        EncodecConfig,
        MusicgenConfig,
        MusicgenForConditionalGeneration,
        T5Config,
    )
    from transformers.models.musicgen.configuration_musicgen import (
        MusicgenDecoderConfig,
    )

    torch.manual_seed(0)
    cfg = MusicgenConfig.from_sub_models_config(
        T5Config(vocab_size=99, d_model=32, d_kv=8, d_ff=64, num_layers=2,
                 num_heads=4, relative_attention_num_buckets=8,
                 decoder_start_token_id=0),
        # frame_rate = 16000/8 = 2000, 6 bits/codebook => 24 kbps = 2
        # quantizer layers, matching the decoder's num_codebooks
        EncodecConfig(target_bandwidths=[24.0], sampling_rate=16000,
                      audio_channels=1, num_filters=8, hidden_size=16,
                      num_residual_layers=1, upsampling_ratios=[4, 2],
                      codebook_size=64, codebook_dim=16, num_lstm_layers=1),
        MusicgenDecoderConfig(vocab_size=64, hidden_size=32,
                              num_hidden_layers=2, num_attention_heads=4,
                              ffn_dim=64, num_codebooks=2,
                              max_position_embeddings=128,
                              pad_token_id=64, bos_token_id=64),
    )
    model = MusicgenForConditionalGeneration(cfg)
    model.generation_config.pad_token_id = 64
    model.generation_config.bos_token_id = 64
    model.generation_config.decoder_start_token_id = 64
    d = tmp_path_factory.mktemp("mg") / "musicgen"
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def _hf(mg_ckpt):
    import torch
    from transformers import MusicgenForConditionalGeneration

    m = MusicgenForConditionalGeneration.from_pretrained(mg_ckpt)
    m.eval()
    return m, torch


def test_t5_encoder_matches_hf(mg_ckpt):
    from localai_tfp_tpu.models.musicgen import load_musicgen, t5_encode

    bundle = load_musicgen(mg_ckpt)
    t5, t5p = bundle[0], bundle[1]
    m, torch = _hf(mg_ckpt)
    ids = np.array([[3, 17, 42, 7, 1]], np.int64)
    with torch.no_grad():
        ref = m.text_encoder(input_ids=torch.tensor(ids)).last_hidden_state
    got = t5_encode(t5, t5p, jnp.asarray(ids.astype(np.int32)))
    np.testing.assert_allclose(np.asarray(got), ref.numpy(),
                               rtol=3e-4, atol=3e-4)


def test_decoder_logits_match_hf(mg_ckpt):
    from localai_tfp_tpu.models.musicgen import (
        load_musicgen, mg_decode_full, t5_encode)

    bundle = load_musicgen(mg_ckpt)
    t5, t5p, dec, dp = bundle[:4]
    m, torch = _hf(mg_ckpt)
    text = np.array([[3, 17, 42]], np.int64)
    codes = np.array([[[0, 5, 9, 2], [0, 11, 3, 7]]], np.int64)  # [1,nb,T]
    with torch.no_grad():
        enc_t = m.text_encoder(input_ids=torch.tensor(text)).last_hidden_state
        out = m.decoder(
            input_ids=torch.tensor(codes.reshape(2, 4)),
            encoder_hidden_states=enc_t,
        ).logits  # [B, nb, T, V]
    enc_j = t5_encode(t5, t5p, jnp.asarray(text.astype(np.int32)))
    if "enc_proj_w" in dp:
        enc_j = enc_j @ dp["enc_proj_w"] + dp["enc_proj_b"]
    got = mg_decode_full(dec, dp, jnp.asarray(codes[0][None]), enc_j)
    np.testing.assert_allclose(np.asarray(got)[0], out.numpy(),
                               rtol=3e-4, atol=3e-4)


def test_encodec_decode_matches_hf(mg_ckpt):
    from localai_tfp_tpu.models.musicgen import encodec_decode, load_musicgen

    bundle = load_musicgen(mg_ckpt)
    enc, ep = bundle[4], bundle[5]
    m, torch = _hf(mg_ckpt)
    rng = np.random.default_rng(0)
    n_q = np.asarray(ep["codebooks"]).shape[0]
    codes = rng.integers(0, 64, (1, 1, n_q, 9))  # [frames, B, nq, T]
    with torch.no_grad():
        ref = m.audio_encoder.decode(
            torch.tensor(codes), [None]).audio_values
    got = encodec_decode(enc, ep, jnp.asarray(codes[0].transpose(1, 0, 2)))
    np.testing.assert_allclose(np.asarray(got), ref[:, 0].numpy(),
                               rtol=2e-3, atol=2e-3)


def test_greedy_generation_matches_hf(mg_ckpt):
    from localai_tfp_tpu.models.musicgen import load_musicgen, mg_generate

    bundle = load_musicgen(mg_ckpt)
    m, torch = _hf(mg_ckpt)
    text = np.array([3, 17, 42, 7], np.int32)
    with torch.no_grad():
        ref = m.generate(
            input_ids=torch.tensor(text[None].astype(np.int64)),
            attention_mask=torch.ones((1, len(text)), dtype=torch.long),
            do_sample=False, guidance_scale=1.0, max_new_tokens=10,
        )
    got = mg_generate(bundle, text, max_new_tokens=10, do_sample=False,
                      guidance_scale=1.0)
    assert got.shape[-1] == ref.shape[-1], (got.shape, ref.shape)
    np.testing.assert_allclose(got, ref[0, 0].numpy(), rtol=2e-3, atol=2e-3)


def test_sampled_generation_is_finite(mg_ckpt):
    from localai_tfp_tpu.models.musicgen import load_musicgen, mg_generate

    bundle = load_musicgen(mg_ckpt)
    text = np.array([5, 9], np.int32)
    wave = mg_generate(bundle, text, max_new_tokens=6, do_sample=True,
                       temperature=1.0, top_k=20, guidance_scale=3.0,
                       seed=4)
    assert wave.ndim == 1 and np.isfinite(wave).all()


def test_sound_generation_worker_uses_musicgen(mg_ckpt, tmp_path):
    import wave

    from localai_tfp_tpu.workers.base import ModelLoadOptions
    from localai_tfp_tpu.workers.tts import JaxTTSBackend

    b = JaxTTSBackend()
    res = b.load_model(ModelLoadOptions(model=mg_ckpt))
    assert res.success, res.message
    assert b._musicgen is not None
    dst = str(tmp_path / "sound.wav")
    r = b.sound_generation("upbeat chiptune", dst=dst, duration=0.01,
                           seed=1)
    assert r.success
    with wave.open(dst, "rb") as w:
        assert w.getframerate() == 16000
        assert w.getnframes() > 0
