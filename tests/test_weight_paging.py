"""Layer-granular weight paging (engine/weight_pager.py): HBM-hot /
host-RAM-warm weight tiers so a gallery of models shares one chip.

The contract under test: ``LOCALAI_WEIGHT_PAGING=off`` is structural
(no pager object at all) and byte-identical — greedy AND seeded
sampling streams match a paging-on all-hot engine exactly; a
demote -> promote round trip is bit-exact per leaf including the int8
``q``/``scale`` planes of quantized projections; promotion re-seeds the
host mirror so the next demotion is a zero-DMA drop; prefetch streams
layers without ever recording a blocking transfer (flight-recorder
evidence); HBM pressure demotes the least-recently-used engine across
the whole process (PagerCoordinator); the HBM ledger attributes
``weights_hot``/``weights_warm`` and keeps host bytes out of the device
drift sum; injected faults on ``weights.demote`` leave the model hot
and serving, on ``weights.fetch`` fall back to one cold blocking load —
the request still serves with exactly one terminal event; and the
watchdog's demote-to-warm mode pages idle models out instead of
killing them, escalating to a kill only after a second full timeout."""

import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from localai_tfp_tpu.config.model_config import ModelConfig
from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
from localai_tfp_tpu.engine.loader import (
    ModelLoader,
    WatchDog,
    registry,
)
from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
from localai_tfp_tpu.engine.weight_pager import COORD
from localai_tfp_tpu.models.llm_spec import tiny_spec
from localai_tfp_tpu.models.quant import QTensor, quantize_params
from localai_tfp_tpu.models.transformer import init_params
from localai_tfp_tpu.telemetry import metrics as tm
from localai_tfp_tpu.telemetry.flightrec import FLIGHT
from localai_tfp_tpu.utils import faultinject as fi
from localai_tfp_tpu.workers.base import Backend, ModelLoadOptions, Result

_KNOBS = ("LOCALAI_WEIGHT_PAGING", "LOCALAI_WEIGHT_HBM_MB",
          "LOCALAI_WEIGHT_PREFETCH_AHEAD", "LOCALAI_WEIGHT_INFLIGHT_MB",
          "LOCALAI_WATCHDOG_DEMOTE")


@pytest.fixture(autouse=True)
def _knob_guard():
    saved = {k: os.environ.get(k) for k in _KNOBS}
    yield
    fi.disarm()
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


@pytest.fixture(scope="module")
def model():
    tk = ByteTokenizer()
    spec = tiny_spec(vocab_size=tk.vocab_size, max_position=256)
    params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
    return spec, params, tk


def _engine(model, paging, **kw):
    spec, params, tk = model
    os.environ["LOCALAI_WEIGHT_PAGING"] = paging
    return LLMEngine(spec, params, tk, n_slots=2, max_seq=128,
                     prefill_buckets=(8, 32), **kw)


def _run(eng, prompt="the quick brown fox", max_tokens=12,
         temperature=0.0, seed=7):
    q = eng.submit(GenRequest(prompt_ids=eng.tokenize(prompt),
                              max_tokens=max_tokens,
                              temperature=temperature, seed=seed,
                              ignore_eos=True))
    toks, finals = [], 0
    while True:
        ev = q.get(timeout=120)
        if ev.token_id is not None:
            toks.append(ev.token_id)
        if ev.done:
            finals += 1
            break
    # drain any stragglers (there must be none: exactly one terminal)
    while not q.empty():
        if q.get_nowait().done:
            finals += 1
    return toks, ev.finish_reason, finals


def _one_shot(model, paging, **gen_kw):
    eng = _engine(model, paging)
    try:
        return _run(eng, **gen_kw)[:2]
    finally:
        eng.close()


def _demote_now(pager, timeout=30.0):
    """Demotions need a quiescent engine; flights can linger a beat
    after the terminal event, so retry the request until it takes."""
    deadline = time.monotonic() + timeout
    while not pager.request_demote():
        assert time.monotonic() < deadline, "engine never went quiet"
        time.sleep(0.01)
    assert pager.settle(timeout)


def _leaves(tree):
    return jax.tree_util.tree_leaves(tree)


# ---------------------------------------------------------------------------
# the off knob: structural removal, byte-identical output


def test_off_knob_is_structural(model):
    off = _engine(model, "off")
    on = _engine(model, "on")
    forced = _engine(model, "on", weight_paging=False)
    try:
        assert off._pager is None
        assert on._pager is not None
        assert forced._pager is None  # ctor override beats the knob
    finally:
        off.close()
        on.close()
        forced.close()


@pytest.mark.slow  # tier-1 representative: the seeded-sampling twin
def test_off_knob_byte_identity_greedy(model):
    a = _one_shot(model, "off")
    b = _one_shot(model, "off")
    c = _one_shot(model, "on")
    assert a == b, "baseline itself is nondeterministic"
    assert a == c, "all-hot paged engine diverged from paging=off"


def test_off_knob_byte_identity_seeded_sampling(model):
    a = _one_shot(model, "off", temperature=0.9, seed=1234)
    b = _one_shot(model, "on", temperature=0.9, seed=1234)
    assert a == b, "seeded sampling diverged under paging"


# ---------------------------------------------------------------------------
# demote -> promote round trip: bit-exact, including int8 planes


def test_round_trip_bit_exact_quantized(model):
    spec, params, tk = model
    qparams = quantize_params(params)
    assert any(isinstance(v, QTensor) for v in qparams.values())
    before = {k: (QTensor(q=np.asarray(v.q), scale=np.asarray(v.scale))
                  if isinstance(v, QTensor) else np.asarray(v))
              for k, v in qparams.items()}
    os.environ["LOCALAI_WEIGHT_PAGING"] = "on"
    eng = LLMEngine(spec, qparams, tk, n_slots=2, max_seq=128,
                    prefill_buckets=(8, 32))
    try:
        pager = eng._pager
        _demote_now(pager)
        assert pager.state == "warm"
        assert eng.params is None
        assert pager.counters["demotes"] == 1
        # a warm engine auto-promotes on the next admission pass
        toks, fin, finals = _run(eng, max_tokens=4)
        assert finals == 1 and toks
        assert pager.state == "hot"
        assert pager.counters["promotes"] == 1
        bl, al = _leaves(before), _leaves(eng.params)
        assert len(bl) == len(al)
        for b, a in zip(bl, al):
            a = np.asarray(a)
            assert b.dtype == a.dtype and b.shape == a.shape
            assert np.array_equal(b, a), "weight bits changed in transit"
        pager.leak_check()
    finally:
        eng.close()


def test_promote_reseeds_host_mirror(model):
    """After a promotion the host mirror still bit-matches the device
    tree, so the NEXT demotion must be a zero-DMA seed drop."""
    eng = _engine(model, "on")
    try:
        pager = eng._pager
        _demote_now(pager)
        _run(eng, max_tokens=2)  # warm -> promote -> serve
        assert pager.state == "hot"
        _demote_now(pager)
        assert pager.counters["demotes"] == 2
        assert pager.counters["seed_demotes"] == 1
        pager.leak_check()
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# prefetch overlap: flight-recorder evidence, no blocking transfers


def test_prefetch_never_records_blocking_transfer(model):
    was = FLIGHT.enabled
    FLIGHT.enabled = True
    eng = _engine(model, "on")
    try:
        pager = eng._pager
        _demote_now(pager)
        FLIGHT.clear()
        _run(eng, max_tokens=4)  # promotion streams the layers back
        assert pager.state == "hot"
        trace = FLIGHT.export_chrome_trace()
        tracks = {ev["tid"]: ev["args"]["name"]
                  for ev in trace["traceEvents"]
                  if ev.get("ph") == "M" and ev["name"] == "thread_name"}
        w = [ev for ev in trace["traceEvents"] if ev.get("ph") == "X"
             and tracks.get(ev["tid"]) == "weights"]
        fetches = [ev for ev in w if ev["name"] == "w:fetch"]
        spec = model[0]
        assert len(fetches) >= spec.n_layers, \
            "promotion did not stream per-layer fetches"
        assert any(ev["name"] == "w:promote" for ev in w)
        assert all(ev["args"]["blocking"] is False for ev in w), \
            "a weight transfer blocked the scheduler"
    finally:
        FLIGHT.enabled = was
        eng.close()


# ---------------------------------------------------------------------------
# cross-engine LRU under HBM pressure


def test_pressure_demotes_lru_engine(model):
    spec, _, tk = model
    pa = init_params(jax.random.PRNGKey(1), spec, dtype=jnp.float32)
    pb = init_params(jax.random.PRNGKey(2), spec, dtype=jnp.float32)
    os.environ["LOCALAI_WEIGHT_PAGING"] = "on"
    ea = LLMEngine(spec, pa, tk, n_slots=2, max_seq=128,
                   prefill_buckets=(8, 32))
    eb = LLMEngine(spec, pb, tk, n_slots=2, max_seq=128,
                   prefill_buckets=(8, 32))
    try:
        a, b = ea._pager, eb._pager
        _run(ea, max_tokens=2)  # A touched first: the LRU victim
        _run(eb, max_tokens=2)
        # budget fits ~1.5 trees: promoting B must evict exactly A
        budget_mb = (a.tree_bytes() * 1.5) / (1 << 20)
        os.environ["LOCALAI_WEIGHT_HBM_MB"] = f"{budget_mb:.6f}"
        _demote_now(b)
        before = COORD.counters["pressure_demotes"]
        _run(eb, max_tokens=2)  # promote -> pressure -> demote A
        assert eb._pager.state == "hot"
        assert COORD.counters["pressure_demotes"] > before
        deadline = time.monotonic() + 30
        while a.state != "warm":
            assert time.monotonic() < deadline, \
                f"LRU victim never went warm (state={a.state})"
            time.sleep(0.01)
        assert ea.params is None
        a.leak_check()
        b.leak_check()
    finally:
        os.environ["LOCALAI_WEIGHT_HBM_MB"] = "0"
        ea.close()
        eb.close()


# ---------------------------------------------------------------------------
# HBM ledger: hot/warm attribution, host bytes out of the drift sum


def test_ledger_hot_warm_reconcile(model):
    eng = _engine(model, "on")
    try:
        pager = eng._pager
        led = eng._ledger
        assert led is not None
        attr = led.attributed()
        assert attr["weights_hot"] == pager.tree_bytes() > 0
        assert attr["weights_warm"] == 0
        assert "weights" not in attr  # replaced by the tiered pair
        _demote_now(pager)
        attr = led.attributed()
        assert attr["weights_hot"] == 0
        assert attr["weights_warm"] == pager.host_bytes() > 0
        snap = led.reconcile(memory_stats=lambda: None)
        # warm bytes live in host RAM: they must not be counted
        # against the device allocation drift
        assert snap["attributed"] == sum(
            b for n, b in snap["components"].items()
            if n != "weights_warm")
        pages = pager.tier_pages()
        assert pages == {"hot": 0, "warm": pager.n_pages}
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# chaos: injected faults on both transfer directions


def test_fault_on_demote_stays_hot_and_serves(model):
    eng = _engine(model, "on")
    try:
        pager = eng._pager
        fi.arm("weights.demote:fail@1")
        deadline = time.monotonic() + 30
        while not pager.request_demote():
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert pager.settle(30)
        assert pager.state == "hot", "faulted demotion must abandon"
        assert eng.params is not None
        assert pager.counters["faulted_demotes"] == 1
        fi.disarm()
        toks, fin, finals = _run(eng, max_tokens=4)
        assert finals == 1 and toks
        pager.leak_check()
    finally:
        eng.close()


def test_fault_on_fetch_falls_back_cold(model):
    ref, _ = _one_shot(model, "off", max_tokens=4)
    eng = _engine(model, "on")
    try:
        pager = eng._pager
        _demote_now(pager)
        fi.arm("weights.fetch:fail@1")
        toks, fin, finals = _run(eng, max_tokens=4)
        fi.disarm()
        assert finals == 1, "fault produced duplicate terminal events"
        assert pager.state == "hot"
        assert pager.counters["cold_fallbacks"] == 1
        assert pager.counters["faulted_fetches"] == 1
        assert toks == ref, "cold-fallback weights diverged"
        pager.leak_check()
    finally:
        eng.close()


# ---------------------------------------------------------------------------
# watchdog demote-to-warm mode


class _PagedBackend(Backend):
    """Scripted demote_weights: first idle tick demotes, later ticks
    report the model already warm (nothing hot left to page out)."""

    def __init__(self):
        self.script = ["demoted", "warm"]
        self.shut = False

    def load_model(self, opts: ModelLoadOptions) -> Result:
        return Result(True)

    def health(self):
        return True

    def shutdown(self):
        self.shut = True

    def demote_weights(self):
        return self.script.pop(0) if self.script else "warm"


def _loader_with(backend_cls):
    saved = dict(registry._factories)
    registry._factories.clear()
    registry.register("jax-llm", backend_cls)
    ml = ModelLoader()
    ml.load(ModelConfig.from_dict({"name": "m", "backend": "jax-llm",
                                   "parameters": {"model": "dir"}}))
    return ml, saved


def test_watchdog_demote_mode(model):
    os.environ["LOCALAI_WATCHDOG_DEMOTE"] = "on"
    ml, saved = _loader_with(_PagedBackend)
    try:
        ml.mark_idle("m")
        wd = WatchDog(ml, idle_timeout=100, enable_idle=True)
        child = tm.MODEL_EVICTIONS.labels(reason="watchdog_demote")
        before = child.value
        # first expiry: demoted, NOT killed, idle clock restarts
        assert wd.check(time.monotonic() + 101) == []
        assert ml.loaded_names() == ["m"]
        assert child.value == before + 1
        # model stays idle through ANOTHER full timeout while warm:
        # the backend reports "warm" and the kill path runs
        assert wd.check(time.monotonic() + 300) == ["m"]
        assert ml.loaded_names() == []
    finally:
        registry._factories.clear()
        registry._factories.update(saved)
        ml.stop_all()


def test_watchdog_demote_busy_transfer_skips_tick(model):
    os.environ["LOCALAI_WATCHDOG_DEMOTE"] = "on"

    class Busy(_PagedBackend):
        def __init__(self):
            super().__init__()
            self.script = ["busy", "busy"]

    ml, saved = _loader_with(Busy)
    try:
        ml.mark_idle("m")
        wd = WatchDog(ml, idle_timeout=10, enable_idle=True)
        # a demotion already aloft: neither demote-count nor kill,
        # the decision is deferred to the next tick
        assert wd.check(time.monotonic() + 11) == []
        assert ml.loaded_names() == ["m"]
    finally:
        registry._factories.clear()
        registry._factories.update(saved)
        ml.stop_all()


def test_watchdog_demote_off_keeps_kill_path(model):
    os.environ["LOCALAI_WATCHDOG_DEMOTE"] = "off"
    ml, saved = _loader_with(_PagedBackend)
    try:
        ml.mark_idle("m")
        wd = WatchDog(ml, idle_timeout=10, enable_idle=True)
        assert wd.check(time.monotonic() + 11) == ["m"]
        assert ml.loaded_names() == []
    finally:
        registry._factories.clear()
        registry._factories.update(saved)
        ml.stop_all()
