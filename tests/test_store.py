"""Vector store semantics (ref: tests/integration/stores_test.go —
normalized and unnormalized cosine paths, upsert, delete, topK)."""

import numpy as np
import pytest

from localai_tfp_tpu.store.backend import LocalStoreBackend, VectorStore


def test_set_get_delete_roundtrip():
    s = VectorStore()
    keys = np.eye(3, dtype=np.float32)
    s.set(keys, ["a", "b", "c"])
    assert len(s) == 3
    got_k, got_v = s.get(keys[1:2])
    assert got_v == ["b"]
    assert np.allclose(got_k, keys[1:2])
    assert s.delete(keys[0:1]) == 1
    assert len(s) == 2
    _, got_v = s.get(keys[0:1])
    assert got_v == []


def test_upsert_replaces_value():
    s = VectorStore()
    k = np.array([[1.0, 0.0]], np.float32)
    s.set(k, ["old"])
    s.set(k, ["new"])
    assert len(s) == 1
    assert s.get(k)[1] == ["new"]


def test_find_normalized_fast_path():
    s = VectorStore()
    keys = np.array([[1, 0], [0, 1],
                     [0.70710678, 0.70710678]], np.float32)
    s.set(keys, ["x", "y", "xy"])
    assert s._normalized
    got_k, got_v, sims = s.find(np.array([1, 0.1], np.float32), 2)
    assert got_v[0] == "x"
    assert len(got_v) == 2
    assert sims[0] >= sims[1]


def test_find_unnormalized_cosine():
    s = VectorStore()
    keys = np.array([[10, 0], [0, 2]], np.float32)  # not unit norm
    s.set(keys, ["big-x", "small-y"])
    assert not s._normalized
    # cosine must ignore magnitude: query along y picks small-y
    _, got_v, sims = s.find(np.array([0, 1], np.float32), 1)
    assert got_v == ["small-y"]
    assert sims[0] == pytest.approx(1.0, abs=1e-5)


def test_topk_clamps_to_size():
    s = VectorStore()
    s.set(np.eye(2, dtype=np.float32), ["a", "b"])
    _, got_v, _ = s.find(np.array([1, 0], np.float32), 10)
    assert len(got_v) == 2


def test_find_empty_store():
    s = VectorStore()
    got_k, got_v, sims = s.find(np.array([1.0], np.float32), 5)
    assert got_v == [] and len(sims) == 0


def test_dim_mismatch_rejected():
    s = VectorStore()
    s.set(np.eye(2, dtype=np.float32), ["a", "b"])
    with pytest.raises(ValueError, match="width"):
        s.set(np.eye(3, dtype=np.float32), ["c", "d", "e"])


def test_backend_wrapper():
    be = LocalStoreBackend()
    assert be.load_model(None).success
    be.stores_set([[1.0, 0.0]], ["v"])
    keys, values, sims = be.stores_find([1.0, 0.0], 1)
    assert values == ["v"] and sims[0] == pytest.approx(1.0)
    keys, values = be.stores_get([[1.0, 0.0]])
    assert values == ["v"]
    be.stores_delete([[1.0, 0.0]])
    assert be.stores_get([[1.0, 0.0]])[1] == []
