"""Learned (silero-class) VAD: every block verified against the
equivalent torch ops with SHARED weights, so a real silero state dict
imports without numeric surprises (ref: backend/go/vad/silero/vad.go
runs the ONNX build of the same network)."""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

from localai_tfp_tpu.models.vad_net import (  # noqa: E402
    CHUNK, CONTEXT, VADParams, init_state, load_state_dict,
    probs_to_segments, speech_probs, vad_forward,
)

BINS, WIN, H = 33, 64, 16
ENC = ((BINS, 24), (24, H))  # (C_in, C_out) per conv layer


def _state_dict(seed=0):
    """Random weights in silero's torchscript key schema."""
    g = torch.Generator().manual_seed(seed)

    def t(*shape, scale=0.3):
        return torch.randn(*shape, generator=g) * scale

    sd = {"_model.stft.forward_basis_buffer": t(2 * BINS, 1, WIN)}
    for i, (cin, cout) in enumerate(ENC):
        sd[f"_model.encoder.{i}.reparam_conv.weight"] = t(cout, cin, 3)
        sd[f"_model.encoder.{i}.reparam_conv.bias"] = t(cout)
    sd["_model.decoder.rnn.weight_ih"] = t(4 * H, H)
    sd["_model.decoder.rnn.weight_hh"] = t(4 * H, H)
    sd["_model.decoder.rnn.bias_ih"] = t(4 * H)
    sd["_model.decoder.rnn.bias_hh"] = t(4 * H)
    sd["_model.decoder.decoder.2.weight"] = t(1, H, 1)
    sd["_model.decoder.decoder.2.bias"] = t(1)
    return sd


def _torch_forward(sd, chunk, h, c):
    """The same network in torch primitives (the golden reference)."""
    x = torch.tensor(chunk)
    basis = sd["_model.stft.forward_basis_buffer"]
    pad = WIN // 2
    x = torch.nn.functional.pad(x[:, None, :], (pad, pad), mode="reflect")
    spec = torch.nn.functional.conv1d(x, basis, stride=WIN // 2)
    mag = torch.sqrt(spec[:, :BINS] ** 2 + spec[:, BINS:] ** 2 + 1e-12)
    hfeat = mag
    for i in range(len(ENC)):
        hfeat = torch.nn.functional.conv1d(
            hfeat, sd[f"_model.encoder.{i}.reparam_conv.weight"],
            sd[f"_model.encoder.{i}.reparam_conv.bias"], padding=1)
        hfeat = torch.relu(hfeat)
    feat = hfeat.mean(dim=-1)
    cell = torch.nn.LSTMCell(H, H)
    cell.weight_ih.data = sd["_model.decoder.rnn.weight_ih"]
    cell.weight_hh.data = sd["_model.decoder.rnn.weight_hh"]
    cell.bias_ih.data = sd["_model.decoder.rnn.bias_ih"]
    cell.bias_hh.data = sd["_model.decoder.rnn.bias_hh"]
    with torch.no_grad():
        h2, c2 = cell(feat, (torch.tensor(h), torch.tensor(c)))
        logit = torch.nn.functional.conv1d(
            torch.relu(h2)[:, :, None],
            sd["_model.decoder.decoder.2.weight"],
            sd["_model.decoder.decoder.2.bias"])
        prob = torch.sigmoid(logit)[:, 0, 0]
    return prob.numpy(), h2.numpy(), c2.numpy()


def test_forward_matches_torch_exactly():
    sd = _state_dict()
    params = load_state_dict(sd)
    rng = np.random.default_rng(1)
    chunk = rng.standard_normal((2, CONTEXT + CHUNK)).astype(np.float32)
    h0 = rng.standard_normal((2, H)).astype(np.float32) * 0.1
    c0 = rng.standard_normal((2, H)).astype(np.float32) * 0.1
    want_p, want_h, want_c = _torch_forward(sd, chunk, h0, c0)
    got_p, got_h, got_c = vad_forward(
        params, chunk, np.asarray(h0), np.asarray(c0))
    np.testing.assert_allclose(np.asarray(got_p), want_p,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_h), want_h,
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got_c), want_c,
                               rtol=1e-4, atol=1e-5)


def test_streaming_state_carries():
    """Same audio split into chunks must give different probs than a
    zero-state restart (the LSTM actually carries memory)."""
    params = load_state_dict(_state_dict())
    rng = np.random.default_rng(2)
    audio = rng.standard_normal(CHUNK * 4).astype(np.float32)
    probs = speech_probs(params, audio)
    assert probs.shape == (4,)
    # restart state at chunk 2: second prob differs from streamed run
    h, c = init_state(1, H)
    chunk2 = np.zeros((1, CONTEXT + CHUNK), np.float32)
    chunk2[0, CONTEXT:] = audio[CHUNK:2 * CHUNK]
    chunk2[0, :CONTEXT] = audio[CHUNK - CONTEXT:CHUNK]
    p_fresh, _, _ = vad_forward(params, chunk2, h, c)
    assert abs(float(p_fresh[0]) - float(probs[1])) > 1e-6


def test_probs_to_segments_hysteresis():
    probs = np.array([0.1, 0.9, 0.8, 0.4, 0.4, 0.9, 0.1, 0.1, 0.1])
    segs = probs_to_segments(probs, threshold=0.5, min_speech_s=0.05,
                             min_silence_s=0.07)
    assert len(segs) == 1  # the 0.4 dip is above neg_threshold: bridged
    s, e = segs[0]
    assert s <= 0.04 and e > 0.15


def test_probs_to_segments_splits_on_silence():
    probs = np.array([0.9, 0.9, 0.05, 0.05, 0.05, 0.9, 0.9, 0.05, 0.05,
                      0.05])
    segs = probs_to_segments(probs, threshold=0.5, min_speech_s=0.03,
                             min_silence_s=0.06)
    assert len(segs) == 2


def test_worker_loads_learned_model(tmp_path):
    """The VAD worker runs learned weights when configured (ref verdict:
    /vad runs learned weights when configured; DSP stays the fallback)."""
    from safetensors.numpy import save_file

    from localai_tfp_tpu.workers.base import ModelLoadOptions
    from localai_tfp_tpu.workers.vad import JaxVADBackend

    sd = {k: v.numpy() for k, v in _state_dict().items()}
    path = str(tmp_path / "vad.safetensors")
    save_file(sd, path)
    b = JaxVADBackend()
    res = b.load_model(ModelLoadOptions(model=path,
                                        options=["threshold=0.5"]))
    assert res.success and "learned" in res.message
    rng = np.random.default_rng(3)
    out = b.vad(list(rng.standard_normal(CHUNK * 6).astype(np.float32)))
    assert isinstance(out.segments, list)  # learned path executed

    # no model => DSP fallback still works
    b2 = JaxVADBackend()
    res2 = b2.load_model(ModelLoadOptions())
    assert res2.success and "DSP" in res2.message


def test_worker_missing_model_fails_loudly():
    from localai_tfp_tpu.workers.base import ModelLoadOptions
    from localai_tfp_tpu.workers.vad import JaxVADBackend

    b = JaxVADBackend()
    res = b.load_model(ModelLoadOptions(model="/nope/silero.jit"))
    assert not res.success and "not found" in res.message
