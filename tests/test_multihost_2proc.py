"""REAL two-process multihost validation: leader + follower in separate
jax.distributed processes on CPU (gloo collectives), exercising the
actual JaxBroadcastChannel transport — not the in-process LocalChannel.

The reference has no automated multi-node tests at all (SURVEY.md §4);
this is the "multi-host sim via jax.distributed on CPU" it calls for.
Each process runs the identical engine; the leader serves requests and
publishes dispatch records over broadcast_one_to_all, the follower
replays them, and both print a digest of their final KV cache — which
must match bitwise."""

import os
import subprocess
import sys

_WORKER = r"""
import hashlib, os, sys
pid = int(sys.argv[1])
port = sys.argv[2]
import jax
jax.config.update("jax_cpu_collectives_implementation", "gloo")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=2,
                           process_id=pid)
jax.config.update("jax_default_matmul_precision", "highest")
import jax.numpy as jnp
import numpy as np
from localai_tfp_tpu.engine.engine import GenRequest, LLMEngine
from localai_tfp_tpu.engine.tokenizer import ByteTokenizer
from localai_tfp_tpu.models.llm_spec import tiny_spec
from localai_tfp_tpu.models.transformer import init_params
from localai_tfp_tpu.parallel import multihost

tk = ByteTokenizer()
spec = tiny_spec(vocab_size=tk.vocab_size, max_position=512)
params = init_params(jax.random.PRNGKey(0), spec, dtype=jnp.float32)
channel = multihost.JaxBroadcastChannel()
kw = dict(n_slots=2, max_seq=128, prefill_buckets=(8, 32),
          cache_dtype=jnp.float32, decode_steps=4)

if pid == 0:
    eng = LLMEngine(spec, params, tk, channel=channel, **kw)
    reqs = [
        GenRequest(prompt_ids=tk.encode("two proc hello"), max_tokens=5,
                   ignore_eos=True),
        GenRequest(prompt_ids=tk.encode("abc"), max_tokens=5,
                   temperature=0.7, seed=9, ignore_eos=True),
    ]
    texts = []
    for q in eng.submit_many(reqs):
        while True:
            ev = q.get(timeout=120)
            if ev.done:
                texts.append(ev.full_text)
                break
    eng.close()
    channel.publish("stop", None)
    assert all(t is not None for t in texts)
else:
    eng = LLMEngine(spec, params, tk, follower=True, **kw)
    multihost.run_follower_engine(eng, channel)

digest = hashlib.sha256(
    np.ascontiguousarray(np.asarray(eng.cache.k)).tobytes()
    + np.ascontiguousarray(np.asarray(eng.cache.v)).tobytes()
).hexdigest()
print(f"DIGEST {pid} {digest}", flush=True)
"""


def test_two_process_leader_follower_bitwise_identical(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = tmp_path / "worker.py"
    script.write_text(_WORKER)
    # a clean env: the axon sitecustomize and TPU plugin must not grab
    # the backend, and PYTHONPATH must point at the repo only
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "JAX_PLATFORMS", "XLA_FLAGS",
                        "PALLAS_AXON_POOL_IPS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = repo
    import socket

    with socket.socket() as s:  # ephemeral port: concurrent runs must
        s.bind(("127.0.0.1", 0))  # not collide on a fixed coordinator
        port = str(s.getsockname()[1])
    procs = [subprocess.Popen(
        [sys.executable, str(script), str(i), port],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
    ) for i in range(2)]
    digests = {}
    logs = []
    for i, p in enumerate(procs):
        out, _ = p.communicate(timeout=540)
        text = out.decode()
        logs.append(text)
        assert p.returncode == 0, f"proc {i} failed:\n{text[-3000:]}"
        for line in text.splitlines():
            if line.startswith("DIGEST"):
                _, pid, digest = line.split()
                digests[int(pid)] = digest
    assert set(digests) == {0, 1}, logs
    assert digests[0] == digests[1], (
        "leader and follower KV caches diverged", logs)
