"""Typed request validation (ref: core/schema request structs): malformed
bodies must 400 with the offending field named, not 500 from deep inside
an endpoint."""

import pytest
from aiohttp import web

from localai_tfp_tpu.server import schema


def test_chat_request_valid():
    req = schema.ChatCompletionRequest.validate({
        "messages": [{"role": "user", "content": "hi"},
                     {"role": "user", "content": [{"type": "text",
                                                   "text": "x"}]}],
        "temperature": 0.5, "max_tokens": 10, "stop": ["a"],
        "logit_bias": {"5": -100},
    })
    assert len(req.messages) == 2


@pytest.mark.parametrize("body", [
    {},  # missing messages
    {"messages": "hi"},
    {"messages": [{"role": 3, "content": "x"}]},
    {"messages": [{"content": 42}]},
    {"messages": [{"content": "x"}], "temperature": "hot"},
    {"messages": [{"content": "x"}], "max_tokens": 1.5},
    {"messages": [{"content": "x"}], "max_tokens": True},
    {"messages": [{"content": "x"}], "stop": [1]},
    {"messages": [{"content": "x"}], "logit_bias": [1]},
    {"messages": [{"content": "x"}], "stream": "yes"},
    {"messages": [{"content": "x"}], "tools": "t"},
])
def test_chat_request_invalid(body):
    with pytest.raises(web.HTTPBadRequest):
        schema.ChatCompletionRequest.validate(body)


def test_completion_and_embeddings_and_rerank():
    schema.CompletionRequest.validate({"prompt": ["a", "b"], "top_k": 4})
    schema.EmbeddingsRequest.validate({"input": ["x", "y"]})
    schema.RerankRequest.validate({"query": "q", "documents": ["d"],
                                   "top_n": 1})
    for body, cls in [
        ({"prompt": {"bad": 1}}, schema.CompletionRequest),
        ({"input": 42}, schema.EmbeddingsRequest),
        ({"query": 1, "documents": ["d"]}, schema.RerankRequest),
        ({"query": "q", "documents": "d"}, schema.RerankRequest),
        ({"query": "q", "documents": ["d"], "top_n": "one"},
         schema.RerankRequest),
    ]:
        with pytest.raises(web.HTTPBadRequest):
            cls.validate(body)


def test_sound_generation_duration_aliases():
    r = schema.SoundGenerationRequest.validate(
        {"text": "x", "duration_seconds": 2.5})
    assert r.duration == 2.5
    r = schema.SoundGenerationRequest.validate(
        {"text": "x", "duration": 1, "temperature": 0})
    assert r.duration == 1.0 and r.temperature == 0.0
    with pytest.raises(web.HTTPBadRequest):
        schema.SoundGenerationRequest.validate({"duration_seconds": "long"})
