"""Tier-1 gate for graftlint (tools/lint): per-rule fixtures, pragma +
baseline semantics, and the whole-repo clean run.

Each rule is proven BOTH ways — it fires on a violating snippet and
stays silent on a clean one — through the lint engine in-memory
(``lint_sources``), so the rules are tested without touching the repo.
The whole-repo tests then pin the real tree at zero non-baselined
findings, which is what makes seeding a violation into
``engine/engine.py`` fail tier-1.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tools.lint import (  # noqa: E402
    ALL_RULES, apply_baseline, lint_repo, lint_sources, load_context,
    run_rules,
)
from tools.lint.rules import rules_by_id  # noqa: E402

ENGINE_REL = "localai_tfp_tpu/engine/engine.py"
MULTIHOST_REL = "localai_tfp_tpu/parallel/multihost.py"


@pytest.fixture(scope="module")
def repo_ctx():
    """One parse of the package shared by the whole-repo tests (the
    seeding tests copy the module list before mutating it)."""
    return load_context(ROOT)


def _ids(findings):
    return [f.rule for f in findings]


def _lint(src, rule, rel="pkg/mod.py", extra=None, readme=""):
    sources = {rel: textwrap.dedent(src)}
    if extra:
        sources.update(extra)
    return lint_sources(sources, rules=rules_by_id([rule]),
                        readme_text=readme)


# --------------------------------------------------------- hot-path-sync


def _hot(body, cold="pass"):
    src = (
        "import numpy as np\n\n"
        "class Eng:\n"
        "    # lint: region hot_path\n"
        "    def step(self):\n"
        + textwrap.indent(textwrap.dedent(body), "        ")
        + "    # lint: endregion hot_path\n\n"
        "    def cold(self):\n"
        + textwrap.indent(textwrap.dedent(cold), "        ") + "\n")
    return lint_sources({"pkg/mod.py": src},
                        rules=rules_by_id(["hot-path-sync"]))


def test_hot_path_item_fires():
    fs = _hot("x = self.cache.k.item()\n")
    assert _ids(fs) == ["hot-path-sync"]


def test_hot_path_block_until_ready_fires():
    fs = _hot("import jax\njax.block_until_ready(self.cache.k)\n")
    assert _ids(fs) == ["hot-path-sync"]


def test_hot_path_tainted_asarray_fires():
    fs = _hot("toks = self._run('decode1', {})\nh = np.asarray(toks)\n")
    assert _ids(fs) == ["hot-path-sync"]


def test_hot_path_int_of_device_value_fires():
    fs = _hot("toks = self._run('decode1', {})\nv = int(toks[0])\n")
    assert _ids(fs) == ["hot-path-sync"]


def test_hot_path_host_conversions_clean():
    # np.asarray on host-built data, metadata access, len() — all fine
    fs = _hot("""\
        pos0 = np.asarray([1, 2], np.int32)
        n = self.cache.k.shape[0] * self.cache.k.dtype.itemsize
        m = int(len(pos0)) + int(n)
        """)
    assert fs == []


def test_hot_path_outside_region_silent():
    fs = _hot("pass\n", cold="y = self.cache.k.item()")
    assert fs == []


def test_hot_path_conversion_result_untaints():
    # once harvested to host (the flagged+suppressed asarray), further
    # int() coercions are free — only ONE finding, at the sync point
    fs = _hot("""\
        toks = self._run('decode1', {})
        h = np.asarray(toks)
        v = int(h[0])
        """)
    assert len(fs) == 1 and fs[0].message.startswith("np.asarray")


def test_hot_path_sync_through_helper_chain_fires():
    """Interprocedural: a .item() buried two helper calls below the
    region fires, and the finding names the call chain."""
    src = """\
        class Eng:
            # lint: region hot_path
            def step(self):
                self._emit()
            # lint: endregion hot_path

            def _emit(self):
                self._deep()

            def _deep(self):
                v = self.cache.k.item()
        """
    fs = _lint(src, "hot-path-sync")
    assert len(fs) == 1, fs
    assert "via step -> _emit -> _deep" in fs[0].message


def test_hot_path_helper_return_taint_fires_and_len_clean():
    """A helper RETURNING a device value taints its callers; a helper
    returning host data (len) does not."""
    src = """\
        class Eng:
            # lint: region hot_path
            def step(self):
                v = int(self._grab())
                n = int(self._count())
            # lint: endregion hot_path

            def _grab(self):
                return self.cache.k

            def _count(self):
                return len(self.slots)
        """
    fs = _lint(src, "hot-path-sync")
    assert len(fs) == 1, fs
    assert "int(" in fs[0].message


# --------------------------------------------------------- scalar-payload


WHITELIST_FIXTURE = {
    "pkg/codec.py": "PAYLOAD_FIELDS = {'kvcopy': ('src', 'dst', 'n')}\n"
}


def _payload(src):
    return _lint(src, "scalar-payload", extra=WHITELIST_FIXTURE)


def test_scalar_payload_clean():
    fs = _payload("""\
        class Eng:
            def go(self):
                self._run("kvcopy", {"src": 1, "dst": 2, "n": 4})
        """)
    assert fs == []


def test_scalar_payload_unknown_field_fires():
    fs = _payload("""\
        class Eng:
            def go(self):
                self._run("kvcopy", {"src": 1, "dst": 2, "evil": object()})
        """)
    assert _ids(fs) == ["scalar-payload"] and "'evil'" in fs[0].message


def test_scalar_payload_unknown_kind_fires():
    fs = _payload("""\
        class Eng:
            def go(self):
                self._run("teleport", {"src": 1})
        """)
    assert _ids(fs) == ["scalar-payload"] and "teleport" in fs[0].message


def test_scalar_payload_resolves_name_and_stores():
    fs = _payload("""\
        class Eng:
            def go(self, paged):
                payload = {"src": 1, "dst": 2}
                if paged:
                    payload["n"] = 8
                    payload["oops"] = 9
                self._run("kvcopy", payload)
        """)
    assert _ids(fs) == ["scalar-payload"] and "'oops'" in fs[0].message


def test_scalar_payload_spread_and_branch_rebuild():
    # **spread of a local dict literal resolves; per-branch rebuilds
    # resolve to the nearest assignment before each call
    fs = _payload("""\
        class Eng:
            def go(self, b):
                base = {"src": 1, "dst": 2}
                payload = {**base, "n": 4}
                self._run("kvcopy", payload)
                payload = {**base, "bad": 0}
                self._run("kvcopy", payload)
        """)
    assert _ids(fs) == ["scalar-payload"] and "'bad'" in fs[0].message


def test_scalar_payload_nonliteral_kind_fires():
    fs = _payload("""\
        class Eng:
            def go(self, kind):
                self._run(kind, {"src": 1})
        """)
    assert _ids(fs) == ["scalar-payload"]


def test_scalar_payload_unresolvable_payload_fires():
    fs = _payload("""\
        class Eng:
            def go(self):
                self._run("kvcopy", self.mk())
        """)
    assert _ids(fs) == ["scalar-payload"]


def test_scalar_payload_forwarding_wrapper_exempt():
    fs = _payload("""\
        class Eng:
            def warm(self):
                def _warm(kind, payload):
                    return self._run(kind, payload)
                _warm("kvcopy", {"src": 0, "dst": 0, "n": 1})
        """)
    assert fs == []


# ------------------------------------------------------------- guarded-by


def test_guarded_by_fires_and_clean():
    src = """\
        import threading

        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._models = {}  # lint: guarded-by self._lock

            def good(self, k, v):
                with self._lock:
                    self._models[k] = v

            def bad(self, k):
                self._models.pop(k, None)
        """
    fs = _lint(src, "guarded-by")
    assert _ids(fs) == ["guarded-by"]
    assert fs[0].scope == "Reg.bad"


def test_guarded_by_holds_pragma_and_init_exempt():
    src = """\
        import threading

        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._models = {}  # lint: guarded-by self._lock
                self._models["init"] = 1  # constructor: exempt

            def helper(self, k):
                # lint: holds self._lock
                del self._models[k]

            def outer(self, k):
                with self._lock:
                    self.helper(k)
        """
    assert _lint(src, "guarded-by") == []


def test_guarded_by_mutating_method_calls():
    src = """\
        import threading

        class Reg:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []  # lint: guarded-by self._lock

            def bad(self, x):
                self._q.append(x)

            def read_ok(self):
                return len(self._q)
        """
    fs = _lint(src, "guarded-by")
    assert _ids(fs) == ["guarded-by"] and "append" not in fs[0].scope


def test_guarded_by_unattached_pragma_is_error():
    src = """\
        class Reg:
            def nothing(self):
                pass  # lint: guarded-by self._lock
        """
    fs = _lint(src, "guarded-by")
    assert _ids(fs) == ["lint-pragma"]


# ------------------------------------------------------- donate-after-use


def test_donation_use_after_fires():
    src = """\
        import jax
        from functools import partial

        class Eng:
            def _fn_factory(self):
                @partial(jax.jit, donate_argnums=(0,))
                def _step(cache, toks):
                    return cache
                return _step

            def bad(self):
                fn = self._fn_factory()
                out = fn(self.cache, 1)
                return self.cache.k
        """
    fs = _lint(src, "donate-after-use")
    assert _ids(fs) == ["donate-after-use"]
    assert "'self.cache'" in fs[0].message


def test_donation_rebind_clean():
    src = """\
        import jax
        from functools import partial

        class Eng:
            def _fn_factory(self):
                @partial(jax.jit, donate_argnums=(0,))
                def _step(cache, toks):
                    return cache
                return _step

            def good(self):
                fn = self._fn_factory()
                self.cache = fn(self.cache, 1)
                return self.cache.k
        """
    assert _lint(src, "donate-after-use") == []


def test_donation_star_args_resolution():
    src = """\
        import jax
        from functools import partial

        class Eng:
            def _fn_factory(self):
                @partial(jax.jit, donate_argnums=(2,))
                def _step(params, toks, cache):
                    return cache
                return _step

            def bad(self, paged):
                fn = self._fn_factory()
                args = [self.params, 1]
                args += [self.cache]
                out = fn(*args)
                return self.cache
        """
    fs = _lint(src, "donate-after-use")
    assert _ids(fs) == ["donate-after-use"]


def test_donation_jitted_attr_binding():
    src = """\
        import jax
        from functools import partial

        class Eng:
            def __init__(self):
                @partial(jax.jit, donate_argnums=(0,))
                def _decode(cache):
                    return cache
                self._decode_fn = _decode

            def good(self):
                self.cache = self._decode_fn(self.cache)

            def bad(self):
                out = self._decode_fn(self.cache)
                return self.cache
        """
    fs = _lint(src, "donate-after-use")
    assert len(fs) == 1 and fs[0].scope == "Eng.bad"


# --------------------------------------------------------- except-swallow


def test_except_swallow_fires():
    src = """\
        def f():
            try:
                work()
            except Exception:
                pass
        """
    assert _ids(_lint(src, "except-swallow")) == ["except-swallow"]


def test_bare_except_fires():
    src = """\
        def f():
            try:
                work()
            except:
                return None
        """
    assert _ids(_lint(src, "except-swallow")) == ["except-swallow"]


@pytest.mark.parametrize("body", [
    "raise ValueError('no')",
    "log.warning('failed: %r', e)",
    "tm.RECOVERED_ERRORS.labels(site='x').inc()",
    "out = str(e)",
])
def test_except_handled_clean(body):
    src = f"""\
        def f():
            try:
                work()
            except Exception as e:
                {body}
        """
    assert _lint(src, "except-swallow") == []


def test_narrow_except_clean():
    src = """\
        def f():
            try:
                work()
            except (KeyError, ValueError):
                pass
        """
    assert _lint(src, "except-swallow") == []


# ------------------------------------------------------- metrics-contract


def test_metrics_contract_suffix_and_case():
    src = """\
        M = REGISTRY.counter("badName", "help")
        N = REGISTRY.gauge("thing_seconds", "help")
        O = REGISTRY.histogram("lat_parsecs", "help")
        """
    fs = _lint(src, "metrics-contract",
               readme="`badName` `thing_seconds` `lat_parsecs`")
    msgs = " | ".join(f.message for f in fs)
    assert "not snake_case" in msgs
    assert "lacks a unit suffix" in msgs and "badName" in msgs


def test_metrics_contract_readme_and_computed():
    src = """\
        name = compute()
        M = REGISTRY.counter(name, "help")
        N = REGISTRY.counter("good_total", "help")
        """
    fs = _lint(src, "metrics-contract", readme="no row here")
    msgs = " | ".join(f.message for f in fs)
    assert "computed name" in msgs
    assert "not documented" in msgs


def test_metrics_contract_clean():
    src = 'M = REGISTRY.counter("good_total", "help")\n'
    assert _lint(src, "metrics-contract", readme="| `good_total` |") == []


# ----------------------------------------------------------- span-balance


def test_span_balance_clean_try_finally():
    src = """\
        def f(rid):
            tok = TRACER.begin_span(rid, "upstream")
            try:
                work()
            finally:
                TRACER.end_span(tok, node="n1")
        """
    assert _lint(src, "span-balance") == []


def test_span_balance_context_manager_clean():
    src = """\
        def f(rid):
            with TRACER.span(rid, "decode"):
                work()
        """
    assert _lint(src, "span-balance") == []


def test_span_balance_missing_try():
    src = """\
        def f(rid):
            tok = TRACER.begin_span(rid, "upstream")
            work()
            TRACER.end_span(tok)
        """
    fs = _lint(src, "span-balance")
    assert _ids(fs) == ["span-balance"]
    assert "not protected" in fs[0].message


def test_span_balance_end_span_not_in_finally():
    src = """\
        def f(rid):
            tok = TRACER.begin_span(rid, "upstream")
            try:
                work()
                TRACER.end_span(tok)
            except Exception:
                pass
        """
    fs = _lint(src, "span-balance")
    assert _ids(fs) == ["span-balance"]
    assert "not protected" in fs[0].message


def test_span_balance_discarded_token():
    src = """\
        def f(rid):
            TRACER.begin_span(rid, "upstream")
            try:
                work()
            finally:
                TRACER.end_span(None)
        """
    fs = _lint(src, "span-balance")
    assert _ids(fs) == ["span-balance"]
    assert "discarded or buried" in fs[0].message


def test_span_balance_buried_in_expression():
    src = """\
        def f(rid):
            toks = [TRACER.begin_span(rid, "a"), TRACER.begin_span(rid, "b")]
            try:
                work()
            finally:
                for t in toks:
                    TRACER.end_span(t)
        """
    fs = _lint(src, "span-balance")
    assert _ids(fs) == ["span-balance", "span-balance"]


# ------------------------------------------------------ sharding-contract

ENG_REL = "localai_tfp_tpu/engine/mod.py"


def test_sharding_unpinned_gather_and_scatter_fire():
    src = """\
        def fallback(self, cache, phys, page):
            win = gather_kv_pages(cache, phys, page)
            win = self.fwd(win)
            scatter_kv_pages(cache, win, wb, page)
        """
    fs = _lint(src, "sharding-contract", rel=ENG_REL)
    msgs = " | ".join(f.message for f in fs)
    assert len(fs) == 2, fs
    assert "batch=True" in msgs and "batch=False" in msgs


def test_sharding_pinned_round_trip_clean():
    src = """\
        def fallback(self, cache, phys, page, mesh):
            win = gather_kv_pages(cache, phys, page)
            win = _pin_win_sharding(win, mesh, batch=True)
            win = self.fwd(win)
            win = _pin_win_sharding(win, mesh, batch=False)
            scatter_kv_pages(cache, win, wb, page)
        """
    assert _lint(src, "sharding-contract", rel=ENG_REL) == []


def test_sharding_inline_spec_literal_fires_in_scope_only():
    src = """\
        from jax.sharding import PartitionSpec as P

        def f(x):
            return P("data", None)
        """
    fs = _lint(src, "sharding-contract", rel=ENG_REL)
    assert len(fs) == 1 and "inline PartitionSpec" in fs[0].message
    # same source outside engine//ops/ is out of scope
    assert _lint(src, "sharding-contract",
                 rel="localai_tfp_tpu/models/mod.py") == []


def test_sharding_constrained_page_table_fires():
    src = """\
        def f(phys, mesh, spec):
            phys = with_sharding_constraint(phys, spec)
            return phys
        """
    fs = _lint(src, "sharding-contract", rel=ENG_REL)
    assert len(fs) == 1 and "host-owned page table" in fs[0].message


# ------------------------------------------------------ env-knob-registry

_KNOBS_FIXTURE = {
    "localai_tfp_tpu/config/knobs.py": (
        'def _knob(n, d, k, doc):\n    pass\n\n'
        '_knob("LOCALAI_FOO", "on", "flag", "a documented knob")\n'
    ),
}


def test_env_knob_raw_access_fires():
    src = """\
        import os

        def f():
            a = os.environ.get("LOCALAI_FOO")
            b = os.environ["LOCALAI_FOO"]
            c = os.getenv(f"LOCALAI_{name}")
            d = os.environ.get("PATH")
        """
    fs = _lint(src, "env-knob-registry", extra=dict(_KNOBS_FIXTURE),
               readme="`LOCALAI_FOO`")
    assert len(fs) == 3, fs  # PATH is not a knob
    assert any("computed" in f.message for f in fs)


def test_env_knob_unregistered_accessor_fires_registered_clean():
    src = """\
        from localai_tfp_tpu.config import knobs

        def f():
            good = knobs.flag("LOCALAI_FOO")
            typo = knobs.flag("LOCALAI_FO0")
            dyn = knobs.str_(name)
        """
    fs = _lint(src, "env-knob-registry", extra=dict(_KNOBS_FIXTURE),
               readme="`LOCALAI_FOO`")
    assert len(fs) == 2, fs
    assert any("UNREGISTERED" in f.message for f in fs)
    assert any("non-literal" in f.message for f in fs)


def test_env_knob_config_dir_exempt():
    src = 'import os\nV = os.environ.get("LOCALAI_FOO")\n'
    assert _lint(src, "env-knob-registry",
                 rel="localai_tfp_tpu/config/app_config.py",
                 extra=dict(_KNOBS_FIXTURE),
                 readme="`LOCALAI_FOO`") == []


def test_env_knob_registry_semantics():
    """The registry accessors read the environment at CALL time with
    forgiving parsers (the rule's promise that one parser serves every
    site)."""
    import os

    from localai_tfp_tpu.config import knobs

    assert "LOCALAI_PAGED_KV" in knobs.REGISTRY
    key = "LOCALAI_PAGED_KV"
    old = os.environ.pop(key, None)
    try:
        assert knobs.flag(key) is True          # default on
        os.environ[key] = "off"
        assert knobs.flag(key) is False         # no caching
        os.environ[key] = "garbage"
        assert knobs.flag(key) is True          # unknown -> default
        os.environ["LOCALAI_KV_PAGE"] = "not-an-int"
        assert knobs.int_("LOCALAI_KV_PAGE") == 0
    finally:
        os.environ.pop(key, None)
        os.environ.pop("LOCALAI_KV_PAGE", None)
        if old is not None:
            os.environ[key] = old
    with pytest.raises(KeyError):
        knobs.flag("LOCALAI_NOT_A_KNOB")
    rows = knobs.markdown_rows()
    assert len(rows) == len(knobs.REGISTRY) and all(
        r.startswith("| `LOCALAI_") for r in rows)


# ------------------------------------------- suppressions, regions, pragmas


def test_ignore_pragma_suppresses_same_and_next_line():
    src = """\
        def f():
            try:
                work()
            # lint: ignore[except-swallow] probe may fail on CPU backends
            except Exception:
                pass
        """
    assert _lint(src, "except-swallow") == []


def test_ignore_without_reason_is_error_and_does_not_suppress():
    src = """\
        def f():
            try:
                work()
            # lint: ignore[except-swallow]
            except Exception:
                pass
        """
    fs = _lint(src, "except-swallow")
    assert sorted(_ids(fs)) == ["except-swallow", "lint-pragma"]


def test_ignore_unknown_rule_is_error():
    fs = _lint("x = 1  # lint: ignore[no-such-rule] because\n",
               "except-swallow")
    assert _ids(fs) == ["lint-pragma"]


def test_unclosed_region_is_error():
    fs = _lint("# lint: region hot_path\nx = 1\n", "hot-path-sync")
    assert _ids(fs) == ["lint-pragma"]
    assert "never closed" in fs[0].message


# ----------------------------------------------------- baseline semantics


def test_baseline_grandfathers_shrinks_and_rejects_new():
    fs = _lint("""\
        def f():
            try:
                work()
            except Exception:
                pass
        """, "except-swallow")
    assert len(fs) == 1
    fp = fs[0].fingerprint
    # exact budget: grandfathered, nothing new, nothing stale
    res = apply_baseline(fs, {fp: 1})
    assert res.ok and len(res.grandfathered) == 1 and not res.new
    # no budget: the finding is new
    res = apply_baseline(fs, {})
    assert not res.ok and len(res.new) == 1
    # over-budget entry: the unmatched remainder is stale — the
    # baseline must SHRINK when findings are fixed
    res = apply_baseline(fs, {fp: 2})
    assert not res.ok and res.stale == [fp]
    res = apply_baseline([], {fp: 1})
    assert not res.ok and res.stale == [fp]


# ------------------------------------------------------- whole-repo gates


def test_repo_lints_clean(repo_ctx):
    """THE gate: zero non-baselined findings across the package with
    all ten rules active. Seeding any violation into the tree (e.g. a
    device sync in engine.py's hot path, a non-codec payload field, an
    unpinned paged-fallback window, a raw LOCALAI_* env read) fails
    here."""
    from tools.lint import DEFAULT_BASELINE, load_baseline

    findings = run_rules(repo_ctx, ALL_RULES)
    res = apply_baseline(findings, load_baseline(DEFAULT_BASELINE))
    assert res.ok, (
        "graftlint found new findings (fix them or, for a reasoned "
        "exception, add a `# lint: ignore[rule] why` pragma):\n"
        + "\n".join(f.render() for f in res.new)
        + "\n".join(f"stale baseline entry: {s}" for s in res.stale))


def test_repo_has_annotations_and_regions(repo_ctx):
    """The contract annotations this PR introduced must stay present —
    deleting a pragma would silently disable its rule's coverage."""
    ctx = repo_ctx
    eng = ctx.module(ENGINE_REL)
    assert len(eng.pragmas.regions.get("hot_path", [])) >= 4
    mh = ctx.module(MULTIHOST_REL)
    assert mh.pragmas.guarded, "multihost guarded-by annotations gone"
    for rel in ("localai_tfp_tpu/engine/loader.py",
                "localai_tfp_tpu/engine/kv_pool.py",
                "localai_tfp_tpu/telemetry/registry.py"):
        assert ctx.module(rel).pragmas.guarded, f"{rel}: no guarded-by"


def test_seeded_hot_path_violation_fires(repo_ctx):
    """Acceptance: seeding a device sync into engine.py's scheduler
    loop makes the lint gate fail."""
    from tools.lint.core import Context
    eng = repo_ctx.module(ENGINE_REL)
    anchor = "        self._apply_cancellations()"
    assert eng.source.count(anchor) == 1
    seeded = eng.source.replace(
        anchor, "        self.cache.k.item()\n" + anchor)
    from tools.lint.core import Module
    mods = list(repo_ctx.modules)
    mods[mods.index(eng)] = Module(ENGINE_REL, seeded)
    ctx = Context(root=ROOT, modules=mods,
                  readme_text=repo_ctx.readme_text)
    findings = run_rules(ctx, rules_by_id(["hot-path-sync"]))
    assert any(f.rule == "hot-path-sync" and "item" in f.message
               for f in findings)


def test_seeded_scalar_payload_violation_fires(repo_ctx):
    """Acceptance: a dispatch field outside the codec whitelist in
    engine.py fails the lint gate."""
    from tools.lint.core import Context
    eng = repo_ctx.module(ENGINE_REL)
    seeded = eng.source + textwrap.dedent("""\


        def _seeded_dispatch(self):
            self._run("kvcopy", {"src": 0, "dst": 0, "n": 1,
                                 "rogue_field": object()})
        """)
    from tools.lint.core import Module
    mods = list(repo_ctx.modules)
    mods[mods.index(eng)] = Module(ENGINE_REL, seeded)
    ctx = Context(root=ROOT, modules=mods,
                  readme_text=repo_ctx.readme_text)
    findings = run_rules(ctx, rules_by_id(["scalar-payload"]))
    assert any(f.rule == "scalar-payload"
               and "rogue_field" in f.message for f in findings)


def test_seeded_unpinned_paged_fallback_fires(repo_ctx):
    """Acceptance: a paged fallback seeded into engine.py that gathers
    and scatters a window without the _pin_win_sharding round trip
    fails the lint gate."""
    from tools.lint.core import Context, Module
    eng = repo_ctx.module(ENGINE_REL)
    seeded = eng.source + textwrap.dedent("""\


        def _seeded_fallback(cache, phys, wb, page, fwd):
            win = gather_kv_pages(cache, phys, page)
            win = fwd(win)
            scatter_kv_pages(cache, win, wb, page)
        """)
    mods = list(repo_ctx.modules)
    mods[mods.index(eng)] = Module(ENGINE_REL, seeded)
    ctx = Context(root=ROOT, modules=mods,
                  readme_text=repo_ctx.readme_text)
    findings = run_rules(ctx, rules_by_id(["sharding-contract"]))
    assert any("batch=True" in f.message for f in findings)
    assert any("batch=False" in f.message for f in findings)


def test_metrics_families_shared_by_import():
    """tools/check_metrics.py and the metrics-contract rule must share
    ONE required-family list — by import identity, not by copy (a fork
    would let the two gates drift apart)."""
    from tools import check_metrics
    from tools.lint.rules import metrics_contract

    assert check_metrics.REQUIRED_FAMILIES is \
        metrics_contract.REQUIRED_FAMILIES
    assert check_metrics.SUFFIXES is metrics_contract.SUFFIXES


def test_cli_json_clean_with_changed_filter():
    """One CLI round trip covers both gates: `--json` report shape AND
    the `--changed` incremental filter (a subset of a clean run is
    still clean, so the combination must also exit 0)."""
    out = subprocess.run(
        [sys.executable, "-m", "tools.lint", "--json", "--changed"],
        capture_output=True, text=True, cwd=ROOT)
    assert out.returncode == 0, out.stdout + out.stderr
    rep = json.loads(out.stdout)
    assert rep["ok"] is True
    assert len(rep["rules"]) == 10
    assert rep["findings"] == [] and rep["stale_baseline"] == []
    assert rep["callgraph_edges"] > 500  # interprocedural graph is live


def test_runtime_codec_validation():
    """The LocalChannel transport enforces PAYLOAD_FIELDS at publish
    time (the dynamic half of the scalar-payload contract)."""
    from localai_tfp_tpu.parallel import multihost

    ch = multihost.LocalChannel()
    end = ch.follower_end()
    ch.publish("kvcopy", {"model": "m",
                          "data": {"src": 0, "dst": 1, "n": 4}})
    kind, rec = end.recv(timeout=1)
    assert kind == "kvcopy" and rec["data"]["n"] == 4
    with pytest.raises(ValueError, match="rogue"):
        ch.publish("kvcopy", {"model": "m", "data": {"rogue": 1}})
    with pytest.raises(ValueError, match="whitelist"):
        ch.publish("warp", {"model": "m", "data": {}})
    ch.publish("stop", None)  # lifecycle records bypass the codec
