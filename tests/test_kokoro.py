"""Kokoro (StyleTTS2-class) TTS: module-level torch parity + checkpoint
import + worker integration.

The torch reference below mirrors the official Kokoro v0.19 module
structure (AdaIN residual blocks, DurationEncoder, iSTFTNet generator
with harmonic source) using real torch ops (nn.LSTM, torch.stft/istft,
F.interpolate, weight_norm, InstanceNorm1d) as ground truth; the PLBERT
encoder parity is pinned against transformers.AlbertModel directly. The
checkpoint is saved in the official layout ({"net": {module:
state_dict}} with DataParallel "module." prefixes and weight_norm
weight_g/weight_v tensors) so the importer path is what a real
kokoro-v0_19.pth would exercise. Deterministic deviations from upstream
(documented in models/kokoro.py): no random initial harmonic phase, and
injectable source noise (shared here for exact comparison).

Ref: /root/reference/backend/python/kokoro/backend.py (voicepack
selection incl. "+" blending, voice indexing by token count).
"""

import json
import math
import os

import numpy as np
import pytest

torch = pytest.importorskip("torch")
import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402
from torch.nn.utils import weight_norm  # noqa: E402

from localai_tfp_tpu.models.kokoro import (  # noqa: E402
    KokoroSpec,
    is_kokoro_dir,
    load_kokoro,
    pick_voice,
    spec_from_config,
    synthesize_kokoro,
)

# tiny geometry (keeps CPU runtime in seconds)
CFG = {
    "n_token": 20,
    "hidden_dim": 16,
    "style_dim": 8,
    "max_dur": 6,
    "n_layer": 2,
    "text_encoder_kernel_size": 5,
    "decoder_hidden": 24,
    "asr_res_dim": 4,
    "sampling_rate": 24000,
    "plbert": {
        "vocab_size": 20, "hidden_size": 16, "embedding_size": 8,
        "num_attention_heads": 2, "num_hidden_layers": 2,
        "intermediate_size": 24, "max_position_embeddings": 64,
    },
    "istftnet": {
        "upsample_rates": [4, 3],
        # k - u must stay even (= 2*padding) like the official (20,10)/
        # (12,6) pairs, or ConvTranspose1d emits one extra sample
        "upsample_kernel_sizes": [8, 9],
        "upsample_initial_channel": 16,
        "resblock_kernel_sizes": [3, 5],
        "resblock_dilation_sizes": [[1, 3], [1, 3]],
        "gen_istft_n_fft": 8,
        "gen_istft_hop_size": 2,
    },
}


# ---------------------------------------------------------------------------
# torch reference modules (official Kokoro v0.19 structure)
# ---------------------------------------------------------------------------


class AdaIN1d(nn.Module):
    def __init__(self, style_dim, num_features):
        super().__init__()
        self.norm = nn.InstanceNorm1d(num_features, affine=False)
        self.fc = nn.Linear(style_dim, num_features * 2)

    def forward(self, x, s):
        h = self.fc(s)
        h = h.view(h.size(0), h.size(1), 1)
        gamma, beta = torch.chunk(h, chunks=2, dim=1)
        return (1 + gamma) * self.norm(x) + beta


class AdaLayerNorm(nn.Module):
    def __init__(self, style_dim, channels, eps=1e-5):
        super().__init__()
        self.channels, self.eps = channels, eps
        self.fc = nn.Linear(style_dim, channels * 2)

    def forward(self, x, s):  # x [B, T, C]
        h = self.fc(s).view(s.size(0), self.channels * 2, 1)
        gamma, beta = torch.chunk(h, chunks=2, dim=1)
        gamma, beta = gamma.transpose(1, 2), beta.transpose(1, 2)
        x = F.layer_norm(x, (self.channels,), eps=self.eps)
        return (1 + gamma) * x + beta


class ChannelLayerNorm(nn.Module):  # StyleTTS2 "LayerNorm"
    def __init__(self, channels, eps=1e-5):
        super().__init__()
        self.channels, self.eps = channels, eps
        self.gamma = nn.Parameter(torch.ones(channels))
        self.beta = nn.Parameter(torch.zeros(channels))

    def forward(self, x):  # [B, C, T]
        x = x.transpose(1, -1)
        x = F.layer_norm(x, (self.channels,), self.gamma, self.beta,
                         self.eps)
        return x.transpose(1, -1)


class UpSample1d(nn.Module):
    def __init__(self, upsample):
        super().__init__()
        self.upsample = upsample

    def forward(self, x):
        if not self.upsample:
            return x
        return F.interpolate(x, scale_factor=2, mode="nearest")


class AdainResBlk1d(nn.Module):
    def __init__(self, dim_in, dim_out, style_dim, upsample=False):
        super().__init__()
        self.upsample_type = upsample
        self.upsample = UpSample1d(upsample)
        self.learned_sc = dim_in != dim_out
        self.conv1 = weight_norm(nn.Conv1d(dim_in, dim_out, 3, 1, 1))
        self.conv2 = weight_norm(nn.Conv1d(dim_out, dim_out, 3, 1, 1))
        self.norm1 = AdaIN1d(style_dim, dim_in)
        self.norm2 = AdaIN1d(style_dim, dim_out)
        if self.learned_sc:
            self.conv1x1 = weight_norm(
                nn.Conv1d(dim_in, dim_out, 1, 1, 0, bias=False))
        if upsample:
            self.pool = weight_norm(nn.ConvTranspose1d(
                dim_in, dim_in, kernel_size=3, stride=2, groups=dim_in,
                padding=1, output_padding=1))
        else:
            self.pool = nn.Identity()

    def forward(self, x, s):
        sc = self.upsample(x)
        if self.learned_sc:
            sc = self.conv1x1(sc)
        h = self.norm1(x, s)
        h = F.leaky_relu(h, 0.2)
        h = self.pool(h)
        h = self.conv1(h)
        h = self.norm2(h, s)
        h = F.leaky_relu(h, 0.2)
        h = self.conv2(h)
        return (h + sc) / math.sqrt(2)


class TextEncoder(nn.Module):
    def __init__(self, channels, kernel_size, depth, n_symbols):
        super().__init__()
        self.embedding = nn.Embedding(n_symbols, channels)
        self.cnn = nn.ModuleList()
        for _ in range(depth):
            self.cnn.append(nn.Sequential(
                weight_norm(nn.Conv1d(channels, channels, kernel_size,
                                      padding=kernel_size // 2)),
                ChannelLayerNorm(channels),
                nn.LeakyReLU(0.2),
                nn.Dropout(0.2),
            ))
        self.lstm = nn.LSTM(channels, channels // 2, 1,
                            batch_first=True, bidirectional=True)

    def forward(self, x):
        x = self.embedding(x).transpose(1, 2)
        for c in self.cnn:
            x = c(x)
        x, _ = self.lstm(x.transpose(1, 2))
        return x.transpose(1, 2)


class DurationEncoder(nn.Module):
    def __init__(self, sty_dim, d_model, nlayers):
        super().__init__()
        self.lstms = nn.ModuleList()
        for _ in range(nlayers):
            self.lstms.append(nn.LSTM(d_model + sty_dim, d_model // 2, 1,
                                      batch_first=True,
                                      bidirectional=True))
            self.lstms.append(AdaLayerNorm(sty_dim, d_model))

    def forward(self, x, style):  # x [B, D, T]
        T = x.shape[-1]
        s = style[:, :, None].expand(-1, -1, T)  # [B, sty, T]
        x = torch.cat([x, s], dim=1)
        for block in self.lstms:
            if isinstance(block, AdaLayerNorm):
                xt = block(x.transpose(-1, -2), style).transpose(-1, -2)
                x = torch.cat([xt, s], dim=1)
            else:
                xt, _ = block(x.transpose(-1, -2))
                x = xt.transpose(-1, -2)
        return x.transpose(-1, -2)  # [B, T, D+sty]


class ProsodyPredictor(nn.Module):
    def __init__(self, style_dim, d_hid, nlayers, max_dur):
        super().__init__()
        self.text_encoder = DurationEncoder(style_dim, d_hid, nlayers)
        self.lstm = nn.LSTM(d_hid + style_dim, d_hid // 2, 1,
                            batch_first=True, bidirectional=True)
        self.duration_proj = nn.Module()
        self.duration_proj.linear_layer = nn.Linear(d_hid, max_dur)
        self.shared = nn.LSTM(d_hid + style_dim, d_hid // 2, 1,
                              batch_first=True, bidirectional=True)
        self.F0 = nn.ModuleList([
            AdainResBlk1d(d_hid, d_hid, style_dim),
            AdainResBlk1d(d_hid, d_hid // 2, style_dim, upsample=True),
            AdainResBlk1d(d_hid // 2, d_hid // 2, style_dim),
        ])
        self.N = nn.ModuleList([
            AdainResBlk1d(d_hid, d_hid, style_dim),
            AdainResBlk1d(d_hid, d_hid // 2, style_dim, upsample=True),
            AdainResBlk1d(d_hid // 2, d_hid // 2, style_dim),
        ])
        self.F0_proj = nn.Conv1d(d_hid // 2, 1, 1)
        self.N_proj = nn.Conv1d(d_hid // 2, 1, 1)

    def F0Ntrain(self, x, s):
        x, _ = self.shared(x.transpose(-1, -2))
        f0 = x.transpose(-1, -2)
        for block in self.F0:
            f0 = block(f0, s)
        f0 = self.F0_proj(f0)
        n = x.transpose(-1, -2)
        for block in self.N:
            n = block(n, s)
        n = self.N_proj(n)
        return f0.squeeze(1), n.squeeze(1)


class AdaINResBlock1(nn.Module):
    def __init__(self, channels, kernel_size, dilation, style_dim):
        super().__init__()
        self.kernel_size = kernel_size
        self.dilation = dilation
        self.convs1 = nn.ModuleList([
            weight_norm(nn.Conv1d(
                channels, channels, kernel_size, dilation=d,
                padding=(kernel_size * d - d) // 2)) for d in dilation])
        self.convs2 = nn.ModuleList([
            weight_norm(nn.Conv1d(
                channels, channels, kernel_size,
                padding=kernel_size // 2)) for _ in dilation])
        self.adain1 = nn.ModuleList(
            [AdaIN1d(style_dim, channels) for _ in dilation])
        self.adain2 = nn.ModuleList(
            [AdaIN1d(style_dim, channels) for _ in dilation])
        self.alpha1 = nn.ParameterList(
            [nn.Parameter(torch.ones(1, channels, 1)) for _ in dilation])
        self.alpha2 = nn.ParameterList(
            [nn.Parameter(torch.ones(1, channels, 1)) for _ in dilation])

    def forward(self, x, s):
        for c1, c2, n1, n2, a1, a2 in zip(
                self.convs1, self.convs2, self.adain1, self.adain2,
                self.alpha1, self.alpha2):
            xt = n1(x, s)
            xt = xt + (1 / a1) * torch.sin(a1 * xt) ** 2
            xt = c1(xt)
            xt = n2(xt, s)
            xt = xt + (1 / a2) * torch.sin(a2 * xt) ** 2
            xt = c2(xt)
            x = xt + x
        return x


class TorchSTFT(nn.Module):
    def __init__(self, n_fft, hop):
        super().__init__()
        self.n_fft, self.hop = n_fft, hop
        self.window = torch.hann_window(n_fft)

    def transform(self, x):
        sp = torch.stft(x, self.n_fft, self.hop, self.n_fft,
                        window=self.window, return_complex=True)
        return torch.abs(sp), torch.angle(sp)

    def inverse(self, mag, phase):
        return torch.istft(mag * torch.exp(phase * 1j), self.n_fft,
                           self.hop, self.n_fft, window=self.window)


class SourceModuleHnNSF(nn.Module):
    def __init__(self, spec):
        super().__init__()
        self.spec = spec
        self.l_linear = nn.Linear(spec.harmonic_num + 1, 1)

    def forward(self, f0_up, noise):  # f0_up [B, t, 1]
        s = self.spec
        h = s.harmonic_num + 1
        scale = s.total_upsample
        f0h = f0_up * torch.arange(1, h + 1, dtype=torch.float32)
        rad = (f0h / s.sampling_rate) % 1.0
        rad_f = F.interpolate(rad.transpose(1, 2),
                              scale_factor=1.0 / scale, mode="linear")
        phase = torch.cumsum(rad_f, dim=-1) * 2 * math.pi
        phase = F.interpolate(phase * scale, scale_factor=scale,
                              mode="linear")
        sines = torch.sin(phase.transpose(1, 2))
        uv = (f0_up > s.voiced_threshold).float()
        noise = (uv * s.noise_std + (1 - uv) * (s.sine_amp / 3.0)) * noise
        sine_waves = s.sine_amp * sines * uv + noise
        return torch.tanh(self.l_linear(sine_waves))


class Generator(nn.Module):
    def __init__(self, spec):
        super().__init__()
        self.spec = spec
        style_dim = spec.style_dim
        self.m_source = SourceModuleHnNSF(spec)
        self.ups = nn.ModuleList()
        self.noise_convs = nn.ModuleList()
        self.noise_res = nn.ModuleList()
        c0 = spec.upsample_initial_channel
        for i, (u, k) in enumerate(zip(spec.upsample_rates,
                                       spec.upsample_kernel_sizes)):
            self.ups.append(weight_norm(nn.ConvTranspose1d(
                c0 // (2 ** i), c0 // (2 ** (i + 1)), k, u,
                padding=(k - u) // 2)))
            ch = c0 // (2 ** (i + 1))
            if i + 1 < len(spec.upsample_rates):
                stride_f0 = int(np.prod(spec.upsample_rates[i + 1:]))
                self.noise_convs.append(nn.Conv1d(
                    spec.gen_istft_n_fft + 2, ch,
                    kernel_size=stride_f0 * 2, stride=stride_f0,
                    padding=(stride_f0 + 1) // 2))
                self.noise_res.append(
                    AdaINResBlock1(ch, 7, (1, 3, 5), style_dim))
            else:
                self.noise_convs.append(nn.Conv1d(
                    spec.gen_istft_n_fft + 2, ch, kernel_size=1))
                self.noise_res.append(
                    AdaINResBlock1(ch, 11, (1, 3, 5), style_dim))
        self.resblocks = nn.ModuleList()
        for i in range(len(self.ups)):
            ch = c0 // (2 ** (i + 1))
            for k, d in zip(spec.resblock_kernel_sizes,
                            spec.resblock_dilation_sizes):
                self.resblocks.append(
                    AdaINResBlock1(ch, k, d, style_dim))
        self.conv_post = weight_norm(nn.Conv1d(
            ch, spec.gen_istft_n_fft + 2, 7, 1, padding=3))
        self.reflection_pad = nn.ReflectionPad1d((1, 0))
        self.stft = TorchSTFT(spec.gen_istft_n_fft,
                              spec.gen_istft_hop_size)

    def forward(self, x, s, f0, noise):
        spec = self.spec
        f0_up = F.interpolate(f0[:, None], scale_factor=spec.total_upsample,
                              mode="nearest").transpose(1, 2)
        har = self.m_source(f0_up, noise)[:, :, 0]
        har_spec, har_phase = self.stft.transform(har)
        har = torch.cat([har_spec, har_phase], dim=1)
        n_k = len(spec.resblock_kernel_sizes)
        for i in range(len(self.ups)):
            x = F.leaky_relu(x, 0.1)
            x_source = self.noise_convs[i](har)
            x_source = self.noise_res[i](x_source, s)
            x = self.ups[i](x)
            if i == len(self.ups) - 1:
                x = self.reflection_pad(x)
            x = x + x_source
            xs = None
            for j in range(n_k):
                h = self.resblocks[i * n_k + j](x, s)
                xs = h if xs is None else xs + h
            x = xs / n_k
        x = F.leaky_relu(x)
        x = self.conv_post(x)
        bins = spec.gen_istft_n_fft // 2 + 1
        mag = torch.exp(x[:, :bins])
        phase = torch.sin(x[:, bins:])
        return self.stft.inverse(mag, phase)


class Decoder(nn.Module):
    def __init__(self, spec):
        super().__init__()
        dh, sty = spec.decoder_hidden, spec.style_dim
        din, ar = spec.hidden_dim, spec.asr_res_dim
        self.encode = AdainResBlk1d(din + 2, dh, sty)
        self.decode = nn.ModuleList([
            AdainResBlk1d(dh + 2 + ar, dh, sty),
            AdainResBlk1d(dh + 2 + ar, dh, sty),
            AdainResBlk1d(dh + 2 + ar, dh, sty),
            AdainResBlk1d(dh + 2 + ar, spec.upsample_initial_channel,
                          sty, upsample=True),
        ])
        self.F0_conv = weight_norm(
            nn.Conv1d(1, 1, kernel_size=3, stride=2, padding=1))
        self.N_conv = weight_norm(
            nn.Conv1d(1, 1, kernel_size=3, stride=2, padding=1))
        self.asr_res = nn.Sequential(
            weight_norm(nn.Conv1d(din, ar, kernel_size=1)))
        self.generator = Generator(spec)

    def forward(self, asr, f0_curve, n_curve, s, noise):
        f0 = self.F0_conv(f0_curve.unsqueeze(1))
        n = self.N_conv(n_curve.unsqueeze(1))
        x = torch.cat([asr, f0, n], dim=1)
        x = self.encode(x, s)
        asr_res = self.asr_res(asr)
        res = True
        for block in self.decode:
            if res:
                x = torch.cat([x, asr_res, f0, n], dim=1)
            x = block(x, s)
            if block.upsample_type:
                res = False
        return self.generator(x, s, f0_curve, noise)


def build_torch_model(spec: KokoroSpec, seed=0):
    from transformers import AlbertConfig, AlbertModel

    torch.manual_seed(seed)
    bert = AlbertModel(AlbertConfig(
        vocab_size=spec.plbert_vocab, hidden_size=spec.plbert_hidden,
        embedding_size=spec.plbert_embedding,
        num_attention_heads=spec.plbert_heads,
        num_hidden_layers=spec.plbert_layers,
        intermediate_size=spec.plbert_intermediate,
        max_position_embeddings=spec.plbert_max_position,
        num_hidden_groups=1,
    ))
    model = {
        "bert": bert,
        "bert_encoder": nn.Linear(spec.plbert_hidden, spec.hidden_dim),
        "text_encoder": TextEncoder(
            spec.hidden_dim, spec.text_encoder_kernel_size, spec.n_layer,
            spec.n_token),
        "predictor": ProsodyPredictor(
            spec.style_dim, spec.hidden_dim, spec.n_layer, spec.max_dur),
        "decoder": Decoder(spec),
    }
    for m in model.values():
        m.eval()
        # non-degenerate random weights (default init leaves some zeros)
        with torch.no_grad():
            for prm in m.parameters():
                if prm.ndim > 0 and float(prm.abs().sum()) == 0.0:
                    prm.add_(torch.randn_like(prm) * 0.05)
    return model


def torch_generate(model, spec, tokens, ref_s, speed, noise):
    """Mirror of kokoro.py generate() (inference graph)."""
    with torch.no_grad():
        t = torch.tensor(tokens, dtype=torch.long)[None]
        mask = torch.ones_like(t)
        bert_dur = model["bert"](t, attention_mask=mask).last_hidden_state
        d_en = model["bert_encoder"](bert_dur).transpose(-1, -2)
        s = ref_s[:, spec.style_dim:]
        ref = ref_s[:, :spec.style_dim]
        pred = model["predictor"]
        d = pred.text_encoder(d_en, s)
        x, _ = pred.lstm(d)
        duration = torch.sigmoid(
            pred.duration_proj.linear_layer(x)).sum(-1) / speed
        pred_dur = torch.round(duration).clamp(min=1).long()[0]
        aln = torch.zeros(t.shape[1], int(pred_dur.sum()))
        c = 0
        for i, n in enumerate(pred_dur):
            aln[i, c:c + int(n)] = 1
            c += int(n)
        en = d.transpose(-1, -2) @ aln
        f0, n_c = pred.F0Ntrain(en, s)
        t_en = model["text_encoder"](t)
        asr = t_en @ aln
        audio = model["decoder"](asr, f0, n_c, ref, noise)
    return (bert_dur, d, pred_dur, f0, n_c, asr, audio)


@pytest.fixture(scope="module")
def kokoro_dir(tmp_path_factory):
    """Official-layout checkpoint dir: config.json + .pth ({"net": ...},
    one module with DataParallel prefixes) + voices/*.pt."""
    root = tmp_path_factory.mktemp("kokoro")
    spec = spec_from_config(CFG)
    model = build_torch_model(spec)
    net = {}
    for name, m in model.items():
        sd = m.state_dict()
        if name == "decoder":  # exercise the "module." strip path
            sd = {f"module.{k}": v for k, v in sd.items()}
        net[name] = sd
    torch.save({"net": net}, root / "kokoro-tiny.pth")
    (root / "config.json").write_text(json.dumps(CFG))
    vdir = root / "voices"
    vdir.mkdir()
    torch.manual_seed(7)
    torch.save(torch.randn(32, 1, 2 * spec.style_dim) * 0.1,
               vdir / "af.pt")
    torch.save(torch.randn(32, 1, 2 * spec.style_dim) * 0.1,
               vdir / "bf.pt")
    return str(root), model, spec


def test_detect_and_load(kokoro_dir):
    root, _, spec = kokoro_dir
    assert is_kokoro_dir(root)
    jspec, params, voices = load_kokoro(root)
    assert jspec == spec
    assert set(voices) == {"af", "bf"}
    assert voices["af"].shape == (32, 1, 2 * spec.style_dim)
    # weight norm folded: no weight_g/_v survive, folded .weight exists
    assert not any(k.endswith(("weight_g", "weight_v")) for k in params)
    assert "decoder.generator.conv_post.weight" in params
    # DataParallel prefix stripped
    assert "decoder.encode.conv1.weight" in params


def test_full_pipeline_torch_parity(kokoro_dir):
    root, model, spec = kokoro_dir
    _, params, voices = load_kokoro(root)
    tokens = [0, 5, 9, 3, 14, 7, 2, 11, 0]
    ref_np = pick_voice(voices, "af", len(tokens), spec.style_dim)
    ref_t = torch.tensor(ref_np)

    # exact parity needs a shared harmonic-source noise sample: compute
    # the upsampled length from the torch duration prediction first
    bert_ref, d_ref, dur_ref, f0_ref, n_ref, asr_ref, audio_ref = \
        torch_generate(model, spec, tokens, ref_t, 1.0,
                       torch.zeros(1, 1, 1))
    t_up = 2 * int(dur_ref.sum()) * spec.total_upsample
    torch.manual_seed(3)
    noise = torch.randn(1, t_up, spec.harmonic_num + 1)
    *_, audio_ref = torch_generate(model, spec, tokens, ref_t, 1.0, noise)

    from localai_tfp_tpu.models import kokoro as K
    import jax.numpy as jnp

    jspec, p, _ = load_kokoro(root)
    tok = jnp.asarray(np.asarray(tokens, np.int32))[None]
    s_pros = jnp.asarray(ref_np[:, spec.style_dim:])

    # module parity: PLBERT vs transformers.AlbertModel
    bert_jax = K._albert(jspec, p, tok)
    np.testing.assert_allclose(np.asarray(bert_jax),
                               bert_ref.numpy(), rtol=2e-4, atol=2e-4)
    # module parity: duration encoder stack + predicted durations
    dur_jax, d_jax = K.durations(jspec, p, tok, s_pros)
    np.testing.assert_allclose(np.asarray(d_jax), d_ref.numpy(),
                               rtol=2e-4, atol=2e-4)
    assert np.array_equal(np.asarray(dur_jax), dur_ref.numpy())
    # module parity: text encoder (via the aligned asr features)
    t_en = K._text_encoder(jspec, p, tok)
    asr_jax = np.repeat(np.asarray(t_en), np.asarray(dur_jax), axis=-1)
    np.testing.assert_allclose(asr_jax, asr_ref.numpy(),
                               rtol=2e-4, atol=2e-4)
    # module parity: prosody F0/N heads
    en = jnp.repeat(jnp.swapaxes(d_jax, 1, 2), np.asarray(dur_jax),
                    axis=-1)
    f0_jax, n_jax = K._prosody_f0n(jspec, p, en, s_pros)
    np.testing.assert_allclose(np.asarray(f0_jax), f0_ref.numpy(),
                               rtol=2e-3, atol=2e-3)
    np.testing.assert_allclose(np.asarray(n_jax), n_ref.numpy(),
                               rtol=2e-3, atol=2e-3)
    # end-to-end audio with the shared source noise
    audio = synthesize_kokoro(jspec, p, tokens, ref_np,
                              source_noise=noise.numpy())
    ref = audio_ref[0].numpy()
    assert audio.shape == ref.shape
    np.testing.assert_allclose(audio, ref, rtol=5e-3, atol=5e-3)


def test_voice_blending(kokoro_dir):
    root, _, spec = kokoro_dir
    _, _, voices = load_kokoro(root)
    a = pick_voice(voices, "af", 5, spec.style_dim)
    b = pick_voice(voices, "bf", 5, spec.style_dim)
    ab = pick_voice(voices, "af+bf", 5, spec.style_dim)
    np.testing.assert_allclose(ab, (a + b) / 2, rtol=1e-6)
    # token-count indexing clamps to the pack
    long = pick_voice(voices, "af", 999, spec.style_dim)
    assert long.shape == (1, 2 * spec.style_dim)


def test_tts_worker_serves_kokoro(kokoro_dir, tmp_path):
    from localai_tfp_tpu.workers.base import ModelLoadOptions
    from localai_tfp_tpu.workers.tts import JaxTTSBackend

    root, _, _ = kokoro_dir
    be = JaxTTSBackend()
    res = be.load_model(ModelLoadOptions(model=root))
    assert res.success, res.message
    dst = str(tmp_path / "out.wav")
    r = be.tts("hello world", voice="af", dst=dst)
    assert r.success and os.path.exists(dst)
    import wave

    with wave.open(dst) as w:
        assert w.getframerate() == 24000
        assert w.getnframes() > 0
