"""Gallery + downloader tests (offline: file:// URIs only — SURVEY.md §4
notes the reference tests gallery installs from a file:// gallery,
tests/fixtures/gallery_simple.yaml)."""

import hashlib
import os
import time

import pytest
import yaml

from localai_tfp_tpu.gallery.downloader import URI, _sha256
from localai_tfp_tpu.gallery.gallery import (
    GalleryModel, _deep_merge, delete_model, install_model,
    load_gallery_index,
)
from localai_tfp_tpu.gallery.service import GalleryOp, GalleryService


def test_uri_scheme_parsing():
    assert URI("huggingface://org/repo/f.gguf").scheme == "huggingface"
    assert URI("github:org/repo/path/x.yaml@main").scheme == "github"
    assert URI("oci://reg/repo:tag").scheme == "oci"
    assert URI("ollama://gemma:2b").scheme == "ollama"
    assert URI("https://x/y").scheme == "https"
    assert URI("file:///tmp/x").scheme == "file"


def test_uri_resolution():
    assert URI("huggingface://TheBloke/repo/model.gguf").resolve_url() == (
        "https://huggingface.co/TheBloke/repo/resolve/main/model.gguf")
    assert URI("huggingface://o/r/sub/dir/f.bin@br").resolve_url() == (
        "https://huggingface.co/o/r/resolve/br/sub/dir/f.bin")
    assert URI("github:go-skynet/gallery/x.yaml@main").resolve_url() == (
        "https://raw.githubusercontent.com/go-skynet/gallery/main/x.yaml")
    with pytest.raises(ValueError):
        URI("huggingface://only/two").resolve_url()


def test_download_file_uri_and_sha(tmp_path):
    src = tmp_path / "src.bin"
    src.write_bytes(b"model-bytes")
    sha = hashlib.sha256(b"model-bytes").hexdigest()
    dst = str(tmp_path / "out" / "dst.bin")
    out = URI(f"file://{src}").download(dst, sha256=sha)
    assert open(out, "rb").read() == b"model-bytes"
    # wrong sha removes the partial and raises
    with pytest.raises(ValueError):
        URI(f"file://{src}").download(str(tmp_path / "bad.bin"),
                                      sha256="0" * 64)
    assert not os.path.exists(str(tmp_path / "bad.bin"))


def test_deep_merge():
    assert _deep_merge({"a": 1, "b": {"x": 1, "y": 2}},
                       {"b": {"y": 3}, "c": 4}) == {
        "a": 1, "b": {"x": 1, "y": 3}, "c": 4}


@pytest.fixture()
def gallery_dir(tmp_path):
    blob = tmp_path / "weights.bin"
    blob.write_bytes(b"w" * 64)
    sha = hashlib.sha256(b"w" * 64).hexdigest()
    index = [{
        "name": "tiny-model",
        "description": "a tiny test model",
        "license": "mit",
        "files": [{
            "filename": "weights.bin",
            "uri": f"file://{blob}",
            "sha256": sha,
        }],
        "config": {
            "name": "tiny-model",
            "backend": "jax-llm",
            "parameters": {"model": "weights.bin"},
        },
        "overrides": {"context_size": 512},
    }]
    idx = tmp_path / "index.yaml"
    idx.write_text(yaml.safe_dump(index))
    return tmp_path, idx


def test_install_and_delete(gallery_dir, tmp_path):
    root, idx = gallery_dir
    models = load_gallery_index(f"file://{idx}", "test")
    assert len(models) == 1 and models[0].name == "tiny-model"
    mp = str(tmp_path / "models")
    cfg_path = install_model(models[0], mp)
    cfg = yaml.safe_load(open(cfg_path))
    assert cfg["context_size"] == 512  # override applied
    assert os.path.exists(os.path.join(mp, "weights.bin"))
    assert delete_model("tiny-model", mp)
    assert not os.path.exists(cfg_path)
    assert not os.path.exists(os.path.join(mp, "weights.bin"))
    assert not delete_model("tiny-model", mp)


def test_gallery_service_job_flow(gallery_dir, tmp_path):
    root, idx = gallery_dir
    mp = str(tmp_path / "models")
    svc = GalleryService(mp, [{"name": "test", "url": f"file://{idx}"}])
    avail = svc.available_models()
    assert [m.name for m in avail] == ["tiny-model"]
    assert not avail[0].installed

    job = svc.submit(GalleryOp(gallery_model_name="tiny-model"))
    for _ in range(100):
        st = svc.status(job)
        if st and st.processed:
            break
        time.sleep(0.05)
    assert st.processed and not st.error, st
    assert st.progress == 100.0
    assert os.path.exists(os.path.join(mp, "tiny-model.yaml"))
    # installed flag refreshes
    assert svc.available_models(refresh=True)[0].installed

    # unknown model -> error status, not an exception
    job2 = svc.submit(GalleryOp(gallery_model_name="nope"))
    for _ in range(100):
        st2 = svc.status(job2)
        if st2 and st2.processed:
            break
        time.sleep(0.05)
    assert st2.error


def test_gallery_at_addressing(gallery_dir, tmp_path):
    root, idx = gallery_dir
    svc = GalleryService(str(tmp_path / "m"),
                         [{"name": "test", "url": f"file://{idx}"}])
    assert svc.find("test@tiny-model") is not None
    assert svc.find("other@tiny-model") is None
    assert svc.find("tiny-model").name == "tiny-model"
