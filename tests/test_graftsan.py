"""Tier-1 gate for graftsan (tools/lint/sanitizer.py): the seeded
violations FIRE — a lock-order inversion produces a cycle report with
both threads' stacks, a guarded-attribute rebind outside its lock
produces a guarded-by report — and the clean paths stay silent
(same-site nesting, re-entrant RLocks, Condition wait round-trips,
construction, mutations under the lock).

The whole-repo "zero reports" leg lives where the load is:
``tests/test_chaos.py`` and ``tests/test_engine_stress.py`` run their
scenarios with the sanitizer armed and fail on any report.
"""

from __future__ import annotations

import os
import sys
import threading
from pathlib import Path

import pytest

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT))

from tools.lint import sanitizer as san  # noqa: E402


@pytest.fixture()
def armed():
    """Arm for everything this test creates (fixture locks included),
    always disarm + clear afterwards."""
    san.reset()
    san.arm(include=lambda f: True)
    yield san
    san.disarm()
    san.reset()


def _run_in_thread(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=10)
    assert not t.is_alive()


# ------------------------------------------------------ lock-order graph


def test_seeded_lock_inversion_fires(armed):
    lock_a = threading.Lock()
    lock_b = threading.Lock()

    def forward():
        with lock_a:
            with lock_b:
                pass

    def inverted():
        with lock_b:
            with lock_a:
                pass

    _run_in_thread(forward)
    assert san.reports() == []  # one order alone is fine
    _run_in_thread(inverted)
    reps = san.reports()
    assert len(reps) == 1 and reps[0]["kind"] == "lock-order-cycle"
    r = reps[0]
    # both stacks of the inverting acquire AND of the prior ordering
    assert "inverted" in r["acquire_stack"]
    assert "inverted" in r["held_stack"]
    assert "forward" in r["prior_acquire_stack"]
    assert "forward" in r["prior_held_stack"]
    assert r["held_site"] != r["acquired_site"]
    assert san.stats()["cycles"] == 1


def test_same_site_nesting_is_not_a_cycle(armed):
    # two locks born on ONE line share a creation site — per-instance
    # nesting discipline the site graph cannot order (lockdep needs
    # nesting annotations here too), so no edge and no false cycle
    lock_c, lock_d = threading.Lock(), threading.Lock()

    def one_way():
        with lock_c:
            with lock_d:
                pass

    def other_way():
        with lock_d:
            with lock_c:
                pass

    _run_in_thread(one_way)
    _run_in_thread(other_way)
    assert san.reports() == []


def test_rlock_reentry_no_self_edge(armed):
    r = threading.RLock()
    with r:
        with r:
            pass
    assert san.reports() == []
    assert san.stats()["edges"] == 0


def test_condition_wait_keeps_held_stack_consistent(armed):
    cond = threading.Condition()
    side = threading.Lock()
    woke = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            woke.append(1)
        with side:  # held stack must be empty again here
            pass

    t = threading.Thread(target=waiter)
    t.start()
    import time
    time.sleep(0.2)
    with cond:
        cond.notify_all()
    t.join(timeout=10)
    assert woke == [1]
    assert san.reports() == []


# -------------------------------------------------- dynamic guarded-by


def _probe_child():
    from localai_tfp_tpu.telemetry.registry import Counter
    # unique family name per call: registries may be process-global
    _probe_child.n += 1
    fam = Counter(f"graftsan_probe_{_probe_child.n}_total", "probe")
    return fam.labels()


_probe_child.n = 0


def test_guarded_rebind_outside_lock_fires(armed):
    child = _probe_child()  # construction itself is exempt
    assert san.reports() == []
    child.value = 5.0
    reps = [r for r in san.reports() if r["kind"] == "guarded-by"]
    assert len(reps) == 1, san.reports()
    r = reps[0]
    assert r["attr"] == "value" and r["lock"] == "self._lock"
    assert "test_guarded_rebind_outside_lock_fires" in r["mutation_stack"]
    assert san.stats()["violations"] == 1


def test_guarded_rebind_under_lock_clean(armed):
    child = _probe_child()
    with child._lock:
        child.value += 1.0
    assert san.reports() == []
    assert san.stats()["guarded_checks"] >= 1


def test_guarded_report_carries_holder_stack(armed):
    child = _probe_child()
    with child._lock:   # wrapped lock records its last acquire stack
        child.value = 1.0
    child.value = 2.0   # violation: holder stack = the with above
    reps = [r for r in san.reports() if r["kind"] == "guarded-by"]
    assert len(reps) == 1
    assert "test_guarded_report_carries_holder_stack" in \
        reps[0]["holder_stack"]


# ------------------------------------------------------- arming lifecycle


def test_disarm_restores_factories_and_goes_silent():
    san.reset()
    san.arm(include=lambda f: True)
    wrapped = threading.Lock()
    assert isinstance(wrapped, san._SanLock)
    san.disarm()
    try:
        raw = threading.Lock()
        assert not isinstance(raw, san._SanLock)
        # locks created while armed keep working, silently
        with wrapped:
            pass
        child = _probe_child()
        child.value = 3.0
        assert san.reports() == []
    finally:
        san.reset()


def test_maybe_arm_respects_knob():
    from localai_tfp_tpu.utils.san import maybe_arm

    old = os.environ.pop("LOCALAI_SAN", None)
    try:
        assert maybe_arm() is False
        assert san.stats()["armed"] is False
        os.environ["LOCALAI_SAN"] = "1"
        assert maybe_arm() is True
        assert san.stats()["armed"] is True
    finally:
        san.disarm()
        san.reset()
        os.environ.pop("LOCALAI_SAN", None)
        if old is not None:
            os.environ["LOCALAI_SAN"] = old


def test_guarded_map_covers_annotated_classes():
    """The pragma map parsed from source must cover the classes the
    repo annotates — if the parser regressed to 0 entries, the dynamic
    check would silently check nothing."""
    if not san._STATE.guarded:
        san._STATE.guarded = san._build_guarded_map()
    mods = {mod for mod, _ in san._STATE.guarded}
    assert "localai_tfp_tpu.telemetry.registry" in mods
    assert "localai_tfp_tpu.engine.kv_pool" in mods
    assert "localai_tfp_tpu.engine.loader" in mods
    attrs = san._STATE.guarded[
        ("localai_tfp_tpu.engine.loader", "ModelLoader")]
    assert attrs.get("_models") == "_lock"
