"""Model numerics: our stacked-scan transformer vs HF transformers (torch cpu).

Strategy mirrors the reference's tiny-real-model API tests (SURVEY.md §4:
Qwen2-1.5B Q2_K etc.) scaled down: random-init tiny checkpoints per family,
saved through HF, reloaded by our loader, logits compared exactly in fp32.
"""

import numpy as np
import pytest

import jax.numpy as jnp


def _save_tiny(tmp_path, family: str) -> str:
    import torch
    from transformers import (
        LlamaConfig,
        LlamaForCausalLM,
        PhiConfig,
        PhiForCausalLM,
        Qwen2Config,
        Qwen2ForCausalLM,
    )

    torch.manual_seed(0)
    common = dict(
        vocab_size=128,
        hidden_size=64,
        intermediate_size=128,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=256,
    )
    if family == "llama":
        model = LlamaForCausalLM(LlamaConfig(**common))
    elif family == "qwen2":
        model = Qwen2ForCausalLM(Qwen2Config(**common))
    elif family == "qwen3":
        from transformers import Qwen3Config, Qwen3ForCausalLM

        model = Qwen3ForCausalLM(Qwen3Config(**common, head_dim=16))
    elif family == "gemma2":
        from transformers import Gemma2Config, Gemma2ForCausalLM

        model = Gemma2ForCausalLM(Gemma2Config(
            **common, head_dim=16, query_pre_attn_scalar=16,
            attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
            sliding_window=8, hidden_activation="gelu_pytorch_tanh",
        ))
    elif family == "gemma3":
        from transformers import Gemma3TextConfig, Gemma3ForCausalLM

        cfg = dict(common)
        cfg["num_hidden_layers"] = 7  # crosses a 5-local+1-global boundary
        model = Gemma3ForCausalLM(Gemma3TextConfig(
            **cfg, head_dim=16, query_pre_attn_scalar=16,
            sliding_window=8, rope_local_base_freq=10000.0,
            rope_theta=1000000.0,
        ))
    elif family == "mixtral":
        from transformers import MixtralConfig, MixtralForCausalLM

        model = MixtralForCausalLM(MixtralConfig(
            **common, num_local_experts=4, num_experts_per_tok=2))
    elif family == "qwen3_moe":
        from transformers import Qwen3MoeConfig, Qwen3MoeForCausalLM

        model = Qwen3MoeForCausalLM(Qwen3MoeConfig(
            **common, head_dim=16, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=96, decoder_sparse_step=1,
            mlp_only_layers=[]))
    elif family == "qwen2_moe":
        from transformers import Qwen2MoeConfig, Qwen2MoeForCausalLM

        # mlp_only_layers=[1]: layer 0 sparse + shared expert, layer 1
        # plain dense MLP — exercises the per-layer sparse/dense mix
        model = Qwen2MoeForCausalLM(Qwen2MoeConfig(
            **common, num_experts=4, num_experts_per_tok=2,
            moe_intermediate_size=96, shared_expert_intermediate_size=128,
            decoder_sparse_step=1, mlp_only_layers=[1],
        ))
    elif family == "phi":
        cfg = dict(common)
        cfg["num_key_value_heads"] = 4  # phi has no GQA by default
        model = PhiForCausalLM(PhiConfig(**cfg, partial_rotary_factor=0.5))
    else:
        raise ValueError(family)
    d = tmp_path / family
    model.save_pretrained(d, safe_serialization=True)
    return str(d)


def _hf_logits(model_dir: str, tokens: np.ndarray) -> np.ndarray:
    import torch
    from transformers import AutoModelForCausalLM

    model = AutoModelForCausalLM.from_pretrained(model_dir, torch_dtype=torch.float32)
    model.eval()
    with torch.no_grad():
        out = model(torch.tensor(tokens)).logits
    return out.numpy()


@pytest.mark.parametrize("family", ["llama", "qwen2", "qwen3", "gemma2",
                                    "gemma3", "mixtral", "qwen2_moe",
                                    "qwen3_moe", "phi"])
def test_logits_match_hf(tmp_path, family):
    from localai_tfp_tpu.models.hf_loader import load_params
    from localai_tfp_tpu.models.transformer import KVCache, forward

    model_dir = _save_tiny(tmp_path, family)
    spec, params = load_params(model_dir, dtype=jnp.float32)

    rng = np.random.default_rng(0)
    tokens = rng.integers(0, spec.vocab_size, size=(1, 12), dtype=np.int32)
    ref = _hf_logits(model_dir, tokens)

    cache = KVCache.create(spec, n_slots=2, max_seq=32, dtype=jnp.float32)
    logits, _ = forward(
        spec,
        params,
        jnp.asarray(tokens),
        pos0=jnp.zeros((1,), jnp.int32),
        cache=cache,
        slot_ids=jnp.zeros((1,), jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(logits), ref, rtol=2e-4, atol=2e-4)


def test_incremental_decode_matches_full_prefill(tmp_path):
    """Prefill(n) then decode 1-at-a-time == prefill(n+k): KV cache path."""
    from localai_tfp_tpu.models.hf_loader import load_params
    from localai_tfp_tpu.models.transformer import KVCache, forward

    model_dir = _save_tiny(tmp_path, "llama")
    spec, params = load_params(model_dir, dtype=jnp.float32)
    rng = np.random.default_rng(1)
    toks = rng.integers(0, spec.vocab_size, size=(1, 10), dtype=np.int32)

    cache = KVCache.create(spec, 2, 32, jnp.float32)
    full, _ = forward(
        spec, params, jnp.asarray(toks), jnp.zeros((1,), jnp.int32), cache,
        jnp.ones((1,), jnp.int32),
    )

    cache = KVCache.create(spec, 2, 32, jnp.float32)
    got, cache = forward(
        spec, params, jnp.asarray(toks[:, :6]), jnp.zeros((1,), jnp.int32),
        cache, jnp.ones((1,), jnp.int32),
    )
    outs = [np.asarray(got)[:, -1]]
    for i in range(6, 10):
        logits, cache = forward(
            spec, params, jnp.asarray(toks[:, i : i + 1]),
            jnp.full((1,), i, jnp.int32), cache, jnp.ones((1,), jnp.int32),
        )
        outs.append(np.asarray(logits)[:, 0])
    inc = np.stack(outs, axis=1)
    np.testing.assert_allclose(inc, np.asarray(full)[:, 5:], rtol=2e-4, atol=2e-4)


def test_multi_slot_isolation(tmp_path):
    """Two slots at different offsets don't corrupt each other."""
    from localai_tfp_tpu.models.hf_loader import load_params
    from localai_tfp_tpu.models.transformer import KVCache, forward

    model_dir = _save_tiny(tmp_path, "llama")
    spec, params = load_params(model_dir, dtype=jnp.float32)
    rng = np.random.default_rng(2)
    a = rng.integers(0, spec.vocab_size, size=(1, 8), dtype=np.int32)
    b = rng.integers(0, spec.vocab_size, size=(1, 5), dtype=np.int32)

    # solo run of b
    cache = KVCache.create(spec, 4, 32, jnp.float32)
    solo, _ = forward(spec, params, jnp.asarray(b), jnp.zeros((1,), jnp.int32),
                      cache, jnp.full((1,), 3, jnp.int32))

    # interleaved: a in slot 0, then b in slot 3, then decode both
    cache = KVCache.create(spec, 4, 32, jnp.float32)
    _, cache = forward(spec, params, jnp.asarray(a), jnp.zeros((1,), jnp.int32),
                       cache, jnp.zeros((1,), jnp.int32))
    got, cache = forward(spec, params, jnp.asarray(b), jnp.zeros((1,), jnp.int32),
                         cache, jnp.full((1,), 3, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), np.asarray(solo), rtol=1e-5, atol=1e-5)

    # batched decode step across both slots
    nxt = jnp.asarray([[int(np.asarray(got)[0, -1].argmax())],
                       [int(np.asarray(solo)[0, -1].argmax())]], jnp.int32)
    logits, _ = forward(
        spec, params, nxt, jnp.asarray([8, 5], jnp.int32), cache,
        jnp.asarray([0, 3], jnp.int32),
    )
    assert np.isfinite(np.asarray(logits)).all()
